"""Ladder #3: BERT pretraining (MLM + NSP) with bf16 and semi-auto sharding.

reference workflow: BERT pretraining over fleet semi-auto parallel
(auto_parallel/api.py shard_tensor). TPU-native: SpmdTrainer over a dp
mesh with the model computing its own pretraining loss; dtype='bfloat16'
exercises the AMP-as-dtype-policy path.
"""

import argparse

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--bf16", action="store_true")
    args = ap.parse_args()
    devices = setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.bert import bert_tiny
    from paddle_tpu.parallel import SpmdTrainer
    from paddle_tpu.parallel.spmd import DP_ONLY_RULES

    paddle.seed(0)
    model = bert_tiny()
    opt = optimizer.AdamW(1e-4, parameters=model.parameters())
    mesh = Mesh(np.asarray(devices), ("dp",))

    def mlm_loss(logits, labels):
        # model without labels returns (mlm_logits, nsp_logits);
        # make_loss_fn hands us the first output
        from paddle_tpu.nn import functional as F
        return F.cross_entropy(logits, labels, ignore_index=-100)

    trainer = SpmdTrainer(model, opt, mesh, DP_ONLY_RULES,
                          loss_fn=mlm_loss, batch_spec=P("dp"),
                          dtype="bfloat16" if args.bf16 else None)

    vocab = model.config.vocab_size
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        ids = jnp.asarray(rng.randint(0, vocab,
                                      (args.batch_size, args.seq)), jnp.int32)
        loss = trainer.step((ids, ids))
        print(f"step {step}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
