"""Ladder #4: Llama pretraining with TP x DP x SEP (+ ZeRO) and a sharded
distributed checkpoint.

reference workflow: fleet hybrid parallel (TP layers + sequence parallel +
DygraphShardingOptimizer) and paddle.distributed.checkpoint. TPU-native:
one jitted GSPMD step (SpmdTrainer + LLAMA_SHARDING_RULES); ring attention
covers the sep axis; save_state_dict writes owner-deduped chunk files.
"""

import argparse
import tempfile

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--sep", type=int, default=2)
    ap.add_argument("--sharding", type=int, default=1)
    ap.add_argument("--zero-stage", type=int, default=0)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--save", action="store_true")
    args = ap.parse_args()
    devices = setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import SpmdTrainer, LLAMA_SHARDING_RULES

    grid = np.asarray(devices).reshape(
        1, args.mp, args.sep, args.sharding, args.dp)
    mesh = Mesh(grid, ("pp", "mp", "sep", "sharding", "dp"))

    paddle.seed(0)
    model = paddle.models.llama_tiny()
    opt = optimizer.AdamW(3e-4, parameters=model.parameters())
    trainer = SpmdTrainer(model, opt, mesh, LLAMA_SHARDING_RULES,
                          batch_spec=P("dp", "sep"),
                          sharding_stage=args.zero_stage)

    rng = np.random.RandomState(0)
    batch = 2 * args.dp
    for step in range(args.steps):
        ids = jnp.asarray(
            rng.randint(0, model.config.vocab_size, (batch, args.seq)),
            jnp.int32)
        loss = trainer.step((ids, ids))
        print(f"step {step}: loss={float(loss):.4f}")

    if args.save:
        from paddle_tpu.distributed import checkpoint as dck
        path = tempfile.mkdtemp(prefix="llama_ckpt_")
        dck.save_state_dict(dict(trainer.params), path)
        print(f"sharded checkpoint written to {path}")


if __name__ == "__main__":
    main()
