"""Ladder #4: Llama pretraining with TP x DP x SEP (+ ZeRO) and a sharded
distributed checkpoint, supervised for production failure modes.

reference workflow: fleet hybrid parallel (TP layers + sequence parallel +
DygraphShardingOptimizer) and paddle.distributed.checkpoint. TPU-native:
one jitted GSPMD step (SpmdTrainer + LLAMA_SHARDING_RULES); ring attention
covers the sep axis; save_state_dict writes owner-deduped chunk files.

The loop runs under resilience.TrainSupervisor (RESILIENCE.md): a
non-finite loss skips the batch instead of killing the run, SIGTERM
writes a final checkpoint and exits clean (code 0), and with --ckpt-dir
a restarted process auto-resumes from the last complete checkpoint.
"""

import argparse
import os
import tempfile

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--sep", type=int, default=2)
    ap.add_argument("--sharding", type=int, default=1)
    ap.add_argument("--zero-stage", type=int, default=0)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--save", action="store_true")
    ap.add_argument("--ckpt-dir", default="",
                    help="checkpoint/resume dir (enables every-step saves, "
                    "SIGTERM final checkpoint, and auto-resume)")
    args = ap.parse_args()
    devices = setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.distributed import checkpoint as dck
    from paddle_tpu.parallel import SpmdTrainer, LLAMA_SHARDING_RULES
    from paddle_tpu.resilience import TrainSupervisor

    grid = np.asarray(devices).reshape(
        1, args.mp, args.sep, args.sharding, args.dp)
    mesh = Mesh(grid, ("pp", "mp", "sep", "sharding", "dp"))

    paddle.seed(0)
    model = paddle.models.llama_tiny()
    opt = optimizer.AdamW(3e-4, parameters=model.parameters())
    trainer = SpmdTrainer(model, opt, mesh, LLAMA_SHARDING_RULES,
                          batch_spec=P("dp", "sep"),
                          sharding_stage=args.zero_stage)

    rng = np.random.RandomState(0)
    batch = 2 * args.dp

    def make_batch():
        return jnp.asarray(
            rng.randint(0, model.config.vocab_size, (batch, args.seq)),
            jnp.int32)

    def save_ckpt(step):
        state = dict(trainer.params)
        state["__step__"] = jnp.asarray(step, jnp.int32)
        dck.save_state_dict(state, args.ckpt_dir)

    def load_ckpt():
        if not os.path.exists(os.path.join(args.ckpt_dir, "metadata.json")):
            return None
        state = dict(trainer.params)
        state["__step__"] = jnp.zeros((), jnp.int32)
        dck.load_state_dict(state, args.ckpt_dir)
        trainer.params = {k: state[k] for k in trainer.params}
        return int(state["__step__"])

    sup = TrainSupervisor(
        lambda ids: trainer.step((ids, ids)),
        save_fn=save_ckpt if args.ckpt_dir else None,
        load_fn=load_ckpt if args.ckpt_dir else None,
        checkpoint_every=1 if args.ckpt_dir else 0)
    sup.install_signal_handlers()   # SIGTERM -> final ckpt + clean exit
    start = sup.resume()
    if start:
        print(f"resumed from step {start} ({args.ckpt_dir})")
        for _ in range(start):      # replay the data stream to the step
            make_batch()

    for step in range(start, args.steps):
        loss = sup.step(make_batch())
        if loss is None:
            print(f"step {step}: non-finite loss, batch skipped")
        else:
            print(f"step {step}: loss={loss:.4f}")

    if args.save:
        path = args.ckpt_dir or tempfile.mkdtemp(prefix="llama_ckpt_")
        dck.save_state_dict(dict(trainer.params), path)
        print(f"sharded checkpoint written to {path}")


if __name__ == "__main__":
    main()
