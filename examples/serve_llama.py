"""Continuous-batching LLM serving on the paged KV cache.

Demonstrates paddle_tpu.inference.ContinuousBatchingEngine: requests are
admitted whenever a batch lane and KV blocks are free, every decode tick
serves the whole active batch through ONE compiled step, finished
sequences retire and their blocks recycle mid-flight — the
iteration-level scheduling loop of modern LLM servers, built on a
block-paged KV pool so fragmentation never strands HBM.

Run: python examples/serve_llama.py            (CPU or attached TPU)
     python examples/serve_llama.py --devices 0  # force real devices
"""

import argparse
import time

import numpy as np

from _common import setup_devices

parser = argparse.ArgumentParser()
parser.add_argument("--devices", default=1, type=int,
                    help="virtual CPU devices (0 = use attached hardware)")
args = parser.parse_args()
setup_devices(args.devices)

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.inference import ContinuousBatchingEngine  # noqa: E402
from paddle_tpu.models.llama import (  # noqa: E402
    LlamaConfig, LlamaForCausalLM)

paddle.seed(0)
cfg = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                  num_hidden_layers=2, num_attention_heads=4,
                  max_position_embeddings=512)
model = LlamaForCausalLM(cfg)

engine = ContinuousBatchingEngine(model, num_blocks=96, block_size=8,
                                  max_batch=4, max_blocks_per_seq=24,
                                  prefill_buckets=(16, 32))

rng = np.random.RandomState(7)
requests = []
for i in range(10):   # oversubscribed 10 requests onto 4 lanes
    prompt = rng.randint(0, cfg.vocab_size, (rng.randint(4, 24),))
    rid = engine.add_request(prompt, max_new_tokens=int(rng.randint(4, 16)))
    requests.append((rid, prompt))

t0 = time.time()
results = engine.run()
dt = time.time() - t0

total = sum(len(v) for v in results.values())
print(f"served {len(requests)} requests / {total} tokens "
      f"in {dt:.2f}s on {paddle.device.get_device()}")
for rid, prompt in requests[:3]:
    print(f"  req {rid}: prompt[{len(prompt)}] -> {results[rid]}")
print(f"  ... ({len(requests) - 3} more)")
