"""Ladder #5: GPT-style 4D hybrid parallel — GSPMD dp x mp x sep x sharding
plus the compiled 1F1B pipeline program over pp x dp.

reference workflow: fleet 4D topology (topology.py pp->mp->sep->sharding->dp)
with PipelineParallel 1F1B (pipeline_parallel.py:575). TPU-native: the
GSPMD axes live in one jitted step; pipeline parallelism is its own
shard_map program (LlamaPipeRunner schedule='1F1B').
"""

import argparse

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--seq", type=int, default=16)
    args = ap.parse_args()
    devices = setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import SpmdTrainer, GPT_SHARDING_RULES
    from paddle_tpu.parallel.llama_pipeline import LlamaPipeRunner

    # -- GSPMD axes: dp x mp x sep x sharding (ZeRO-2) -------------------
    n = len(devices)
    if n % 4 != 0:
        raise SystemExit(f"--devices must be a multiple of 4 (mp=2 x sep=2 "
                         f"x dp={max(n // 4, 1)}); got {n}")
    grid = np.asarray(devices).reshape(1, 2, 2, 1, n // 4)
    mesh = Mesh(grid, ("pp", "mp", "sep", "sharding", "dp"))
    paddle.seed(0)
    model = paddle.models.gpt_tiny()
    opt = optimizer.AdamW(3e-4, parameters=model.parameters())
    trainer = SpmdTrainer(model, opt, mesh, GPT_SHARDING_RULES,
                          batch_spec=P("dp", "sep"), sharding_stage=2)
    rng = np.random.RandomState(0)
    batch = 2 * mesh.shape["dp"]
    for step in range(args.steps):
        ids = jnp.asarray(
            rng.randint(0, model.config.vocab_size, (batch, args.seq)),
            jnp.int32)
        loss = trainer.step((ids, ids))
        print(f"[gspmd dp x mp x sep] step {step}: loss={float(loss):.4f}")

    # -- pipeline axis: 1F1B over pp x dp --------------------------------
    pp, pdp = 2, max(n // 4, 1)
    mesh2 = Mesh(np.asarray(devices[: pp * pdp]).reshape(pp, pdp),
                 ("pp", "dp"))
    paddle.seed(0)
    lmodel = paddle.models.llama_tiny(num_hidden_layers=2)
    lopt = optimizer.AdamW(3e-4, parameters=lmodel.parameters())
    runner = LlamaPipeRunner(lmodel, mesh2,
                             num_microbatches=args.microbatches,
                             batch_axis="dp", optimizer=lopt,
                             schedule="1F1B")
    for step in range(args.steps):
        ids = jnp.asarray(
            rng.randint(0, lmodel.config.vocab_size,
                        (args.microbatches * pdp, args.seq)), jnp.int32)
        loss = runner.step(ids, ids)
        print(f"[1F1B pp x dp] step {step}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
