"""Shared example plumbing: CPU-mesh bootstrap for laptop/CI runs."""

import os
import sys

# the repo is used in-place (no pip install): make paddle_tpu importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_devices(n_devices):
    """Force a virtual n-device CPU platform when no TPU slice is attached.
    On a real TPU pod slice, pass --devices 0 to use the attached chips."""
    if n_devices and int(n_devices) > 0:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    return jax.devices()
