"""Compiled KV-cache text generation on a Llama model.

Demonstrates paddle_tpu.generation: one jit covers prefill + the lax.scan
decode loop; greedy and nucleus sampling share the compiled program
(temperature/top_p are traced scalars).
"""

import argparse

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--sample", action="store_true")
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-p", type=float, default=0.9)
    args = ap.parse_args()
    setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle

    paddle.seed(0)
    model = paddle.models.llama_tiny(num_hidden_layers=2)
    prompts = jnp.asarray(
        np.random.RandomState(0).randint(0, model.config.vocab_size, (2, 8)),
        jnp.int32)

    out = model.generate(prompts, max_new_tokens=args.max_new_tokens,
                         do_sample=args.sample,
                         temperature=args.temperature, top_p=args.top_p,
                         seed=0)
    ids = np.asarray(out._data)
    for row in ids:
        prompt, cont = row[:8].tolist(), row[8:].tolist()
        print(f"prompt={prompt} -> {cont}")


if __name__ == "__main__":
    main()
