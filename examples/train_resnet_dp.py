"""Ladder #2: ResNet data-parallel training over a 1-D dp mesh.

reference workflow: fleet DP (paddle.DataParallel + EagerReducer bucketed
allreduce). TPU-native: SpmdTrainer with a dp-only mesh — batch sharded on
'dp', grad reduction inserted by GSPMD onto ICI.
"""

import argparse

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--depth", type=int, default=18)
    ap.add_argument("--image-size", type=int, default=32)
    args = ap.parse_args()
    devices = setup_devices(args.devices)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.parallel import SpmdTrainer
    from paddle_tpu.parallel.spmd import DP_ONLY_RULES
    from paddle_tpu.vision import models as M

    paddle.seed(0)
    model = {18: M.resnet18, 34: M.resnet34, 50: M.resnet50}[args.depth](
        num_classes=10)
    mesh = Mesh(np.asarray(devices), ("dp",))
    opt = optimizer.Momentum(0.01, momentum=0.9,
                             parameters=model.parameters())

    def loss_fn(logits, labels):
        return nn.functional.cross_entropy(logits, labels)

    trainer = SpmdTrainer(model, opt, mesh, DP_ONLY_RULES,
                          loss_fn=loss_fn, batch_spec=P("dp"))
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        x = jnp.asarray(rng.rand(args.batch_size, 3, args.image_size,
                                 args.image_size), jnp.float32)
        y = jnp.asarray(rng.randint(0, 10, (args.batch_size,)), jnp.int32)
        loss = trainer.step((x, y))
        print(f"step {step}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
