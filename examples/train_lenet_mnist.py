"""Ladder #1: LeNet-5 on MNIST with the high-level Model API.

reference workflow: paddle.Model + paddle.vision (hapi/model.py fit:2200).
"""

import argparse

from _common import setup_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--iters", type=int, default=60)
    args = ap.parse_args()
    setup_devices(args.devices)

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer, metric
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.vision.datasets import MNIST

    paddle.seed(0)
    model = paddle.Model(LeNet())
    model.prepare(optimizer.Adam(1e-3, parameters=model.parameters()),
                  nn.CrossEntropyLoss(), metric.Accuracy())
    model.fit(MNIST(mode="train"), epochs=args.epochs,
              batch_size=args.batch_size, num_iters=args.iters, verbose=1)
    res = model.evaluate(MNIST(mode="test"), batch_size=128, verbose=0)
    print(f"test: loss={res['loss'][0]:.4f} acc={float(res['acc']):.4f}")


if __name__ == "__main__":
    main()
