"""Retry policies and circuit breaking for transient runtime faults.

A `RetryPolicy` is jittered exponential backoff with an attempt budget
and an optional wall-clock deadline; a `CircuitBreaker` stops hammering
a dependency that keeps failing and lets it recover. Both are pure-host
stdlib objects applied to the failure-prone seams: TCPStore ops,
checkpoint IO, and the elastic heartbeat/membership watch.

Determinism: jitter comes from a `random.Random(seed)` stream, so a
seeded policy produces the same backoff sequence every run — chaos
drills stay reproducible. Retries and give-ups are counted in the
observability catalog per `op` label (`resilience_retries_total`,
`resilience_retry_giveups_total`, `resilience_circuit_open_total`).
"""

from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "CircuitBreaker", "CircuitOpenError",
           "DEFAULT_TRANSIENT"]

# what "transient" means by default: timeouts, connection blips, and IO
# errors. Anything else (ValueError, RuntimeError, ...) is a logic error
# and must escape immediately.
DEFAULT_TRANSIENT = (TimeoutError, ConnectionError, OSError)


def _count(name, **labels):
    try:
        from ..observability.catalog import metric
        metric(name, **labels).inc()
    except Exception:  # noqa: BLE001 — never fail the op over metrics
        pass


class RetryPolicy:
    """
    policy = RetryPolicy(max_attempts=4, base_delay=0.05, deadline=10)
    value = policy.call(store.get, key, op="store.get")
    """

    def __init__(self, max_attempts=4, base_delay=0.05, max_delay=2.0,
                 deadline=None, jitter=0.5, retry_on=DEFAULT_TRANSIENT,
                 seed=None, sleep=time.sleep, clock=time.monotonic,
                 on_retry=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = None if deadline is None else float(deadline)
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        self._on_retry = on_retry

    def backoff(self, attempt):
        """Delay before retry number `attempt` (1-based): exponential,
        capped, multiplied into [1-jitter, 1] deterministically from the
        seeded stream."""
        d = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    def call(self, fn, *args, op="op", **kwargs):
        """Run fn(*args, **kwargs); retry transient failures with
        backoff until the attempt budget or deadline runs out, then
        re-raise the last exception. Returns (on success) fn's value;
        `.last_retries` holds the retry count of the most recent call."""
        start = self._clock()
        self.last_retries = 0
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                if attempt >= self.max_attempts:
                    _count("resilience_retry_giveups_total", op=op)
                    raise
                delay = self.backoff(attempt)
                if (self.deadline is not None
                        and self._clock() - start + delay > self.deadline):
                    _count("resilience_retry_giveups_total", op=op)
                    raise
                _count("resilience_retries_total", op=op)
                self.last_retries += 1
                if self._on_retry is not None:
                    self._on_retry(op, attempt, e)
                self._sleep(delay)

    def wrap(self, op):
        """Decorator form: @policy.wrap("ckpt.chunk_write")."""
        def deco(fn):
            def inner(*args, **kwargs):
                return self.call(fn, *args, op=op, **kwargs)
            inner.__name__ = getattr(fn, "__name__", op)
            return inner
        return deco


class CircuitOpenError(RuntimeError):
    """Raised instead of calling through while the breaker is open."""


class CircuitBreaker:
    """Classic three-state breaker: CLOSED counts consecutive failures;
    at `failure_threshold` it OPENs (calls fail fast with
    CircuitOpenError) for `reset_timeout` seconds; then one HALF_OPEN
    probe call decides — success closes, failure re-opens."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold=5, reset_timeout=30.0,
                 clock=time.monotonic, op="op"):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self.op = op
        self.state = self.CLOSED
        self.failures = 0
        self._opened_at = None

    def _tick(self):
        if (self.state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout):
            self.state = self.HALF_OPEN

    def allow(self):
        self._tick()
        return self.state != self.OPEN

    def record_success(self):
        self.state = self.CLOSED
        self.failures = 0

    def record_failure(self):
        self.failures += 1
        if (self.state == self.HALF_OPEN
                or self.failures >= self.failure_threshold):
            if self.state != self.OPEN:
                _count("resilience_circuit_open_total", op=self.op)
            self.state = self.OPEN
            self._opened_at = self._clock()

    def call(self, fn, *args, **kwargs):
        if not self.allow():
            raise CircuitOpenError(
                f"circuit {self.op!r} open after {self.failures} "
                f"consecutive failures; retrying after "
                f"{self.reset_timeout}s")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
