"""Deterministic fault-injection harness.

A registry of NAMED fault sites threaded through the runtime's failure-
prone seams (checkpoint IO, store ops, elastic heartbeat, serving
admission/decode, train step). Production code calls
``fault_point("site")`` — a single global-load + None check when the
harness is disarmed, so hot paths pay nothing — and the harness raises
the configured exception class on the configured hit.

Armed two ways:

* ``FLAGS_fault_injection`` (env ``FLAGS_fault_injection=...`` or
  ``paddle.set_flags``) with a spec string, e.g.::

      ckpt.metadata_replace:1:RuntimeError
      store.get:2:TimeoutError;store.set:1:ConnectionError
      store.get:rand(0.2)@42:TimeoutError      # seeded schedule

  Entries are ``site:nth:Exc`` (fire exactly on the nth hit of that
  site) or ``site:rand(p)@seed:Exc`` (each hit fires with probability p
  from a deterministic per-(seed, site) stream — the same seed always
  yields the same schedule).

* programmatically: ``arm([FaultSpec(...)])`` / ``arm_spec(text)`` /
  ``disarm()``, or the ``injected_faults(...)`` context manager tests
  use.

Every injection increments ``fault_injected_total{site=...}`` in the
observability catalog, so a chaos drill can assert that zero injected
faults escaped unhandled while every one was counted.
"""

from __future__ import annotations

import random
import re
import threading

__all__ = ["FAULT_SITES", "FaultSpec", "FaultInjected", "fault_point",
           "check", "arm", "arm_spec", "disarm", "injected_faults",
           "hit_counts", "injected_counts", "parse_spec"]

# The closed set of fault sites. Instrumentation may only reference
# these names (same discipline as the observability metric catalog) —
# arming an unknown site is a spec error, not a silent no-op.
FAULT_SITES = {
    "ckpt.chunk_write": "distributed checkpoint: one chunk .npy write "
                        "(inside the atomic tmp-write + rename)",
    "ckpt.metadata_replace": "distributed checkpoint: between the chunk "
                             "writes and the metadata.json os.replace "
                             "(the kill-mid-save window)",
    "store.get": "TCPStore.get (native or in-process fallback)",
    "store.set": "TCPStore.set (native or in-process fallback)",
    "elastic.heartbeat": "ElasticManager lease beat write",
    "serve.admit": "serving admission: lane + pool reservation for a "
                   "queued request",
    "serve.decode_oom": "serving decode step: device OOM "
                        "(shed-and-requeue path)",
    "serve.prefill_chunk": "serving chunked prefill: one prompt-chunk "
                           "forward (failure aborts the task; request "
                           "requeued at the front for a fresh prefill)",
    "serve.hostsync_read": "serving decode: token-tile device->host "
                           "readback (transient failure keeps the tile "
                           "in flight and retries next step)",
    "serve.draft_verify": "serving speculative decode: draft/verify "
                          "dispatch (failure permanently degrades the "
                          "engine to non-speculative decode; streams "
                          "continue byte-identically)",
    "serve.kv_dequant": "serving quantized KV pool: dequant-fused "
                        "attention read (failure dequantizes the whole "
                        "pool to the native dtype once and drops the "
                        "quantized block format for the engine's "
                        "lifetime)",
    "serve.loadgen_tick": "traffic harness: one open-loop clock tick "
                          "(injected failure models clock skew / a "
                          "stalled driver; the tick is skipped and "
                          "counted, its arrivals re-issued next tick)",
    "serve.sched_decide": "SLO scheduler: the per-step closed-loop "
                          "decision (brownout ladder + preemption "
                          "choice); ANY failure degrades scheduling to "
                          "plain FIFO for the engine's lifetime — "
                          "knobs restored, parked lanes resumed, no "
                          "deadlock, no dropped request",
    "serve.preempt": "SLO scheduler: one decode-lane preemption "
                     "(paged-KV stays resident); failure aborts that "
                     "attempt, counted, and the victim lane keeps "
                     "decoding",
    "serve.adapter_load": "adapter store: hot-load/refcount of a named "
                          "LoRA adapter at admission; ANY failure is a "
                          "typed rejection (finish_reason=rejected, "
                          "serving_rejected_total{reason=adapter}) — "
                          "never a silent base-weights fallback; lanes "
                          "on other adapters are untouched",
    "serve.adapter_gather": "adapter store: lane-bind residency check "
                            "of the slot the fused scan will gather "
                            "A/B factors from; failure rejects the "
                            "request typed + counted instead of "
                            "gathering stale weights",
    "train.step_nonfinite": "train supervisor: force a non-finite loss "
                            "for this step (consulted via check())",
    "compile.cache_read": "PIR compile cache: artifact read (verified "
                          "load of a serialized StableHLO program; "
                          "failure degrades to recompile)",
    "compile.cache_write": "PIR compile cache: artifact write (atomic "
                           "tmp+rename; failure degrades to an uncached "
                           "but working compile)",
    "compile.verify": "PIR structural verifier entry (pir/verifier.py): "
                      "an injected fault is wrapped as the "
                      "verifier-error rule and the compile degrades to "
                      "plain jax.jit, counted "
                      "pir_fallback_total{stage=verify}",
    "compile.fuse": "PIR auto-fusion pass (pir/fuse.py): hit 1 is the "
                    "planning walk (failure degrades that compile to "
                    "plain jax.jit with identical numerics, counted "
                    "pir_fallback_total{stage=fuse}); hits 2+ are "
                    "per-group commits (failure skips THAT group — its "
                    "ops replay unfused, every other group stays "
                    "committed, no fallback)",
    "compile.shard_prop": "PIR sharding-propagation pass entry "
                          "(pir/shard_prop.py): an injected fault "
                          "aborts the pass pipeline and the compile "
                          "degrades to plain UNSHARDED jax.jit with "
                          "identical numerics, counted "
                          "pir_fallback_total{stage=passes}",
    "mesh.route": "mesh router: one replica pick for a queued request "
                  "(failure counts a failover and the request is "
                  "re-routed to the next-best replica; CircuitBreaker "
                  "per replica keeps a flapping target out of the "
                  "rotation)",
    "mesh.kv_handoff": "mesh disaggregation: serialized paged-KV block "
                       "transfer from a prefill worker to a decode "
                       "worker (retry-then-re-prefill: transient "
                       "failure retries the transfer, exhaustion "
                       "re-prefills the request on the decode side — "
                       "streams stay byte-identical either way)",
    "mesh.replica_down": "mesh membership: a replica is killed "
                         "(consulted via check(); the router tombstones "
                         "it, opens its breaker, and re-routes + "
                         "re-prefills its in-flight requests on the "
                         "survivors)",
    "mesh.transport_send": "mesh process transport: one framed "
                           "request/response round trip between router "
                           "and worker (transport.py clients; armed "
                           "BEFORE the frame leaves so a retry is "
                           "always safe — transient failure retries "
                           "under the client RetryPolicy, exhaustion "
                           "surfaces TransportError, and a failed "
                           "paged-KV import re-prefills on the decode "
                           "side, streams byte-identical)",
    "mesh.net_delay": "mesh transport network chaos: one reply held a "
                      "SHORT extra window before it lands (consulted "
                      "via check() on the receive path of both "
                      "transports); the op budget absorbs it — at most "
                      "a counted TransportTimeout and a late settle, "
                      "streams byte-identical, nobody demoted",
    "mesh.net_stall": "mesh transport network chaos: one reply held "
                      "hostage for a LONG gray-failure window (shorter "
                      "than the health detector's dead threshold by "
                      "construction); the detector trips SLOW — "
                      "demoted in ranking, counted "
                      "mesh_slow_demotions_total — BEFORE anything "
                      "trips DEAD, hedged re-prefill covers the stuck "
                      "work, and streams stay byte-identical",
    "mesh.controller_act": "mesh autoscale controller: one act() on an "
                           "AutoscaleAdvisor verdict (controller.py); "
                           "ANY failure latches the controller back to "
                           "advisory-only — counted "
                           "mesh_controller_actions_total"
                           "{action=latch_off} — while serving "
                           "continues byte-identically",
    "serve.prefix_match": "serving prefix cache: one index operation "
                          "(admission-time prompt-prefix lookup, or the "
                          "post-prefill / post-import block insert); ANY "
                          "failure degrades to a plain cache miss — full "
                          "prefill or an unindexed prompt, streams "
                          "byte-identical, never a wrong hit — counted "
                          "serving_runtime_degradations_total"
                          "{what=prefix_miss}",
    "obs.sample": "observability plane: one MetricsSampler scrape tick "
                  "(timeseries.py); ANY failure flips the sampler to "
                  "degraded — plane off, counted "
                  "obs_plane_degradations_total{what} — and serving "
                  "continues byte-identically (the plane is read-only "
                  "by construction)",
}


class FaultInjected(Exception):
    """Default injected exception; also the marker base callers may use
    to distinguish harness-made failures in logs."""


# exception classes a spec may name — a closed set so a typo'd spec
# fails at parse time instead of injecting the wrong thing
_EXC_CLASSES = {
    "FaultInjected": FaultInjected,
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "MemoryError": MemoryError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
}


class FaultSpec:
    """One armed fault: fire `exc` at `site` either exactly on hit
    `nth` (1-based) or on each hit with probability `prob` drawn from a
    deterministic stream seeded by (seed, site)."""

    __slots__ = ("site", "nth", "prob", "seed", "exc", "_rng", "fired")

    def __init__(self, site, nth=None, prob=None, seed=0, exc=FaultInjected):
        if site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {site!r}; registered sites: "
                f"{sorted(FAULT_SITES)}")
        if (nth is None) == (prob is None):
            raise ValueError("FaultSpec needs exactly one of nth / prob")
        self.site = site
        self.nth = None if nth is None else int(nth)
        self.prob = None if prob is None else float(prob)
        self.seed = int(seed)
        self.exc = exc
        self._rng = (random.Random(f"{self.seed}:{site}")
                     if self.prob is not None else None)
        self.fired = 0

    def should_fire(self, hit):
        if self.nth is not None:
            return hit == self.nth
        return self._rng.random() < self.prob

    def __repr__(self):
        when = (f"nth={self.nth}" if self.nth is not None
                else f"rand({self.prob})@{self.seed}")
        return f"FaultSpec({self.site}, {when}, {self.exc.__name__})"


class _Plan:
    __slots__ = ("specs", "hits", "injected", "lock")

    def __init__(self, specs):
        self.specs = list(specs)
        self.hits = {}          # site -> total fault_point passes
        self.injected = {}      # site -> fires
        self.lock = threading.Lock()


_active: _Plan | None = None


def _count_injected(site, hit):
    try:
        from ..observability.catalog import metric
        metric("fault_injected_total", site=site).inc()
    except Exception:  # noqa: BLE001 — injection never fails over metrics
        pass
    try:
        from ..observability.recorder import get_recorder
        rec = get_recorder()
        if rec.enabled:
            rec.record("fault", site=site, hit=hit)
    except Exception:  # noqa: BLE001 — nor over the flight recorder
        pass


def _fire(site, raise_exc):
    """Shared body of fault_point/check; returns the exception instance
    to raise (or True for check()) when a spec fires, else None/False."""
    plan = _active
    if plan is None:
        return None if raise_exc else False
    with plan.lock:
        hit = plan.hits.get(site, 0) + 1
        plan.hits[site] = hit
        spec = None
        for s in plan.specs:
            if s.site == site and s.should_fire(hit):
                spec = s
                break
        if spec is None:
            return None if raise_exc else False
        spec.fired += 1
        plan.injected[site] = plan.injected.get(site, 0) + 1
    _count_injected(site, hit)
    if not raise_exc:
        return True
    return spec.exc(f"injected fault at {site} (hit {hit})")


def fault_point(site, **ctx):
    """Instrumentation hook: raises the armed exception when a spec for
    `site` fires on this hit; otherwise returns immediately. `ctx` is
    documentation-only (what the site was doing)."""
    exc = _fire(site, raise_exc=True)
    if exc is not None:
        raise exc


def check(site):
    """Non-raising variant for sites where the fault is a *behavior*
    rather than an exception (e.g. train.step_nonfinite: the supervisor
    fabricates a NaN loss when this returns True)."""
    return _fire(site, raise_exc=False)


def arm(specs):
    """Arm the harness with FaultSpec instances (replaces any prior
    plan). Empty/None disarms."""
    global _active
    if not specs:
        _active = None
        return
    _active = _Plan(specs)


def disarm():
    arm(None)


_RAND_RE = re.compile(r"^rand\(([0-9.]+)\)(?:@(\d+))?$")


def parse_spec(text):
    """``site:nth:Exc`` / ``site:rand(p)@seed:Exc`` entries joined by
    ``;``. Returns [FaultSpec]; raises ValueError on unknown sites,
    exception names, or malformed entries."""
    specs = []
    for entry in filter(None, (e.strip() for e in text.split(";"))):
        parts = entry.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"malformed fault spec entry {entry!r} "
                "(want site:nth:Exc or site:rand(p)@seed:Exc)")
        site, when, exc_name = (p.strip() for p in parts)
        if exc_name not in _EXC_CLASSES:
            raise ValueError(
                f"unknown exception class {exc_name!r} in fault spec; "
                f"allowed: {sorted(_EXC_CLASSES)}")
        exc = _EXC_CLASSES[exc_name]
        m = _RAND_RE.match(when)
        if m:
            specs.append(FaultSpec(site, prob=float(m.group(1)),
                                   seed=int(m.group(2) or 0), exc=exc))
        else:
            specs.append(FaultSpec(site, nth=int(when), exc=exc))
    return specs


def arm_spec(text):
    """Arm from a FLAGS_fault_injection-style string ('' disarms)."""
    text = (text or "").strip()
    arm(parse_spec(text) if text else None)


class injected_faults:
    """Context manager for tests/drills: arm on enter, restore the
    previous plan on exit.

        with injected_faults("store.get:1:TimeoutError"):
            ...
    """

    def __init__(self, spec):
        self._spec = spec
        self._prev = None

    def __enter__(self):
        global _active
        self._prev = _active
        if isinstance(self._spec, str):
            arm_spec(self._spec)
        else:
            arm(self._spec)
        return _active

    def __exit__(self, *exc_info):
        global _active
        _active = self._prev
        return False


def hit_counts():
    """{site: times fault_point/check was reached} for the active plan
    (empty when disarmed) — the chaos drill's coverage evidence."""
    plan = _active
    if plan is None:
        return {}
    with plan.lock:
        return dict(plan.hits)


def injected_counts():
    plan = _active
    if plan is None:
        return {}
    with plan.lock:
        return dict(plan.injected)


def _arm_from_flag():
    """Honor FLAGS_fault_injection at import (env) — set_flags re-arms
    via the flags side-effect hook."""
    try:
        from ..framework.flags import flag_value
        arm_spec(flag_value("fault_injection"))
    except Exception:  # noqa: BLE001 — flags not defined yet / partial init
        pass


_arm_from_flag()
