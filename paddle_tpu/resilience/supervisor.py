"""Train-step supervisor: non-finite-loss skip, preemption grace,
checkpoint-cadence + auto-resume.

Wraps any step callable (e.g. `SpmdTrainer.step` or a jitted closure)
with the graceful-degradation discipline multi-host TPU training needs:

* **non-finite loss** — a NaN/Inf loss does not kill the run; the batch
  is skipped (counted in `train_nonfinite_skips_total`), optionally the
  last checkpoint is restored, and only a configurable streak of
  consecutive non-finite steps raises the typed `NonFiniteLossError`.
* **preemption** — SIGTERM (what TPU-VM/GKE send before reclaiming a
  node) sets a flag; at the NEXT step boundary the supervisor writes a
  final checkpoint and raises `Preempted`, which subclasses SystemExit
  with code 0 — an unhandled preemption is a *clean* exit, not a crash.
* **auto-resume** — `resume()` reloads the last complete checkpoint via
  the caller's load_fn and restores the step counter, so a restarted
  worker continues the loss curve where the checkpoint left it.

The fault site `train.step_nonfinite` (resilience.faults) lets chaos
drills force the non-finite path deterministically without touching the
model.
"""

from __future__ import annotations

import math
import signal as _signal
import threading

from . import faults

__all__ = ["TrainSupervisor", "NonFiniteLossError", "Preempted"]


def _count(name):
    try:
        from ..observability.catalog import metric
        metric(name).inc()
    except Exception:  # noqa: BLE001 — supervision never fails over metrics
        pass


class NonFiniteLossError(RuntimeError):
    """Too many consecutive non-finite losses: the run is diverging, not
    hitting a transient batch — stop instead of burning the pod."""


class Preempted(SystemExit):
    """Raised at the step boundary after a preemption signal, AFTER the
    final checkpoint is written. Subclasses SystemExit(0): if the train
    script does not catch it, the process still exits cleanly."""

    def __init__(self, step):
        super().__init__(0)
        self.step = step

    def __str__(self):
        return f"preempted at step {self.step} (final checkpoint written)"


class TrainSupervisor:
    """
    sup = TrainSupervisor(trainer.step,
                          save_fn=lambda step: save_ckpt(step),
                          load_fn=load_ckpt,          # -> start step or None
                          checkpoint_every=10)
    sup.install_signal_handlers()                      # SIGTERM grace
    start = sup.resume()
    for s in range(start, total):
        loss = sup.step(batch)                         # None = skipped batch
    """

    def __init__(self, step_fn, save_fn=None, load_fn=None, restore_fn=None,
                 checkpoint_every=0, max_consecutive_nonfinite=3):
        self._step_fn = step_fn
        self._save_fn = save_fn
        self._load_fn = load_fn
        self._restore_fn = restore_fn
        self.checkpoint_every = int(checkpoint_every)
        self.max_consecutive_nonfinite = int(max_consecutive_nonfinite)
        self.step_count = 0
        self.nonfinite_skips = 0
        self._consecutive_nonfinite = 0
        self._preempt = threading.Event()
        self._old_handlers = {}

    # -- preemption --------------------------------------------------------
    def install_signal_handlers(self, signals=(_signal.SIGTERM,)):
        """Register the grace-window handler (main thread only — the
        caller decides; workers under a launcher usually want this)."""
        for sig in signals:
            self._old_handlers[sig] = _signal.signal(
                sig, lambda *_: self._preempt.set())
        return self

    def restore_signal_handlers(self):
        for sig, old in self._old_handlers.items():
            _signal.signal(sig, old)
        self._old_handlers.clear()

    def request_preemption(self):
        """What the signal handler does — callable directly by tests and
        by platform-specific preemption notices (e.g. a metadata-server
        watcher thread)."""
        self._preempt.set()

    @property
    def preemption_requested(self):
        return self._preempt.is_set()

    def _finalize_preemption(self):
        if self._save_fn is not None:
            self._save_fn(self.step_count)
        _count("train_preemptions_total")
        try:
            from ..observability.recorder import get_recorder
            rec = get_recorder()
            if rec.enabled:
                rec.record("preempt", step=self.step_count)
                rec.dump(reason="preempt")
        except Exception:  # noqa: BLE001 — the black box never blocks exit
            pass
        raise Preempted(self.step_count)

    # -- resume ------------------------------------------------------------
    def resume(self):
        """Load the last complete checkpoint (if any) via load_fn; set
        and return the step to continue from. load_fn returning None
        means 'nothing to resume' (fresh start at 0)."""
        start = 0
        if self._load_fn is not None:
            loaded = self._load_fn()
            if loaded is not None:
                start = int(loaded)
        self.step_count = start
        return start

    # -- the supervised step ----------------------------------------------
    def step(self, *batch, **kwargs):
        """One supervised step. Returns the float loss, or None when the
        batch was skipped for a non-finite loss. Raises Preempted at the
        first step boundary after a preemption request (final checkpoint
        already written), NonFiniteLossError on a divergence streak."""
        if self._preempt.is_set():
            self._finalize_preemption()
        loss = self._step_fn(*batch, **kwargs)
        val = float(loss)
        if faults.check("train.step_nonfinite"):
            val = float("nan")
        if not math.isfinite(val):
            self.nonfinite_skips += 1
            self._consecutive_nonfinite += 1
            _count("train_nonfinite_skips_total")
            if self._restore_fn is not None:
                # roll back to the last good checkpoint so a poisoned
                # update cannot propagate
                self._restore_fn()
            if self._consecutive_nonfinite > self.max_consecutive_nonfinite:
                raise NonFiniteLossError(
                    f"{self._consecutive_nonfinite} consecutive non-finite "
                    f"losses at step {self.step_count} (limit "
                    f"{self.max_consecutive_nonfinite}): diverged")
            return None
        self._consecutive_nonfinite = 0
        self.step_count += 1
        if (self.checkpoint_every and self._save_fn is not None
                and self.step_count % self.checkpoint_every == 0):
            self._save_fn(self.step_count)
        return val

    def run(self, batches, total_steps=None):
        """Drive `step` over an iterable of batches (each an args tuple
        for step_fn); returns the list of recorded (finite) losses.
        Stops after total_steps successful steps when given."""
        losses = []
        target = None if total_steps is None else int(total_steps)
        for batch in batches:
            if target is not None and self.step_count >= target:
                break
            loss = self.step(*batch if isinstance(batch, tuple) else (batch,))
            if loss is not None:
                losses.append(loss)
        return losses
