"""Failure handling as a first-class, testable subsystem.

Three pieces, threaded through the distributed/serving runtime:

* `faults` — a deterministic fault-injection harness over a closed
  registry of named sites (`FLAGS_fault_injection`, or `arm()`/
  `injected_faults(...)` in tests); disarmed it costs one global load
  per site.
* `retry` — `RetryPolicy` (jittered exponential backoff + attempt
  budget + deadline) and `CircuitBreaker`, applied to store ops,
  checkpoint IO, and the elastic heartbeat/membership watch.
* `supervisor` — `TrainSupervisor` wrapping train-step callables with
  non-finite-loss skip, SIGTERM preemption grace (final checkpoint +
  clean exit), and checkpoint auto-resume.

Fault sites, retry defaults, the preemption runbook, and the chaos-drill
howto are documented in RESILIENCE.md; every fault, retry, and recovery
increments a counter from the observability catalog (OBSERVABILITY.md).
"""

from __future__ import annotations

from . import faults, retry, supervisor  # noqa: F401
from .faults import (  # noqa: F401
    FAULT_SITES, FaultInjected, FaultSpec, arm, arm_spec, check, disarm,
    fault_point, injected_faults)
from .retry import (  # noqa: F401
    DEFAULT_TRANSIENT, CircuitBreaker, CircuitOpenError, RetryPolicy)
from .supervisor import NonFiniteLossError, Preempted, TrainSupervisor  # noqa: F401

__all__ = ["faults", "retry", "supervisor", "FAULT_SITES", "FaultSpec",
           "FaultInjected", "fault_point", "check", "arm", "arm_spec",
           "disarm", "injected_faults", "RetryPolicy", "CircuitBreaker",
           "CircuitOpenError", "DEFAULT_TRANSIENT", "TrainSupervisor",
           "NonFiniteLossError", "Preempted"]
