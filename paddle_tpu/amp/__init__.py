"""AMP — automatic mixed precision as a dtype policy.

reference: python/paddle/amp/ (auto_cast.py O1/O2 lists, grad_scaler.py,
amp_lists.py). On TPU the native fast dtype is bfloat16, whose dynamic range
matches float32 — so loss scaling is unnecessary (GradScaler degrades to a
pass-through but keeps the dynamic-scale API for parity/float16).

O1 maps to a per-op cast hook on the eager dispatch path (the analog of
AmpAutoCasts in paddle/fluid/eager/amp_auto_cast.h); O2 casts parameters.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..framework import core as _core
from ..framework import dtypes as _dt
from ..framework.core import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler",
           "is_float16_supported", "is_bfloat16_supported",
           "white_list", "black_list"]

# reference: python/paddle/amp/amp_lists.py
WHITE_LIST = {
    "matmul", "mm", "bmm", "linear", "fused_matmul_bias", "conv1d",
    "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "scaled_dot_product_attention", "flash_attention_pallas", "rnn", "lstm",
    "gru", "addmm", "mv",
}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "c_softmax_with_cross_entropy", "layer_norm", "norm",
    "batch_norm", "group_norm", "instance_norm", "rms_norm", "logsumexp",
    "erf", "erfinv", "pow", "log_softmax", "log_sigmoid", "bce",
    "bce_with_logits", "nll_loss", "kl_div", "l1_loss", "mse_loss",
    "smooth_l1_loss", "ctc_loss",
}


def white_list():
    return {"float16": {"O1": WHITE_LIST, "O2": WHITE_LIST},
            "bfloat16": {"O1": WHITE_LIST, "O2": WHITE_LIST}}


def black_list():
    return {"float16": {"O1": BLACK_LIST, "O2": BLACK_LIST},
            "bfloat16": {"O1": BLACK_LIST, "O2": BLACK_LIST}}


class _AmpState:
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def _cast_hook(name, arrs):
    if not _state.enabled:
        return arrs
    target = _state.dtype
    wl = (WHITE_LIST | _state.custom_white) - _state.custom_black
    bl = BLACK_LIST | _state.custom_black
    if name in wl:
        return [a.astype(target)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in arrs]
    if name in bl:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype in (jnp.float16, jnp.bfloat16) else a
                for a in arrs]
    return arrs


_core._amp_cast_hook = _cast_hook


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """reference: python/paddle/amp/auto_cast.py:auto_cast."""
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = _dt.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast model parameters to the target dtype (keeping fp32 master
    weights in the optimizer when master_weight). reference:
    python/paddle/amp/auto_cast.py:decorate."""
    target = _dt.convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        from ..nn.layer.norm import _BatchNormBase, LayerNorm
        excluded = (_BatchNormBase, LayerNorm)
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, excluded):
                    continue
                for p in layer._parameters.values():
                    if p is not None and p._data.dtype == jnp.float32:
                        p._data = p._data.astype(target)
    if optimizers is None:
        return models if isinstance(models, (list, tuple)) else model_list[0]
    return (models if isinstance(models, (list, tuple)) else model_list[0]), optimizers


amp_decorate = decorate


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class GradScaler:
    """Dynamic loss scaling. reference: python/paddle/amp/grad_scaler.py.
    With bf16 (TPU default) scaling is mathematically unnecessary; the
    machinery is kept for float16 parity."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._data * inv
                import jax.numpy as jnp
                found = found or bool(jnp.any(~jnp.isfinite(g)))
                p.grad._data = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        # reference contract (amp/grad_scaler.py:261): the caller has
        # already run scaled_loss.backward(); minimize only unscales,
        # conditionally steps, and updates the scale
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        from ..framework.core import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.asarray(self._scale))

    def state_dict(self):
        return {"scale": self._scale, "good": self._good_steps,
                "bad": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good", 0)
        self._bad_steps = sd.get("bad", 0)

from . import debugging  # noqa: F401,E402
