"""paddle.amp.debugging — numerics debugging utilities.

reference: python/paddle/amp/debugging.py — DebugMode +
TensorCheckerConfig drive the eager NaN/Inf scanner
(fluid/eager/nan_inf_utils), operator-stats collection counts op calls per
dtype, and compare_accuracy diffs two dump directories
(accuracy_compare.py).

TPU-native: the tensor checker IS the FLAGS_check_nan_inf scan wired into
`execute()` (framework/core.py _maybe_check_nan); the config object here
just sets those flags. Operator stats wrap the same dispatcher with a
counting hook. Dumps are .npy files per flagged op, diffable by
compare_accuracy.
"""

from __future__ import annotations

import contextlib
import enum
import os

import numpy as np

import jax.numpy as jnp

from ..framework import flags as _flags
from ..framework.core import Tensor

__all__ = ["DebugMode", "TensorCheckerConfig", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "enable_tensor_checker", "disable_tensor_checker",
           "compare_accuracy", "check_layer_numerics"]


class DebugMode(enum.Enum):
    """reference: amp/debugging.py DebugMode."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    DUMP_ALL = 4
    DUMP_FAIL = 5


class TensorCheckerConfig:
    """reference: amp/debugging.py TensorCheckerConfig — which ops to scan
    and what to do on a hit."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """reference: amp/debugging.py enable_tensor_checker — maps onto
    FLAGS_check_nan_inf(_level): ABORT -> level 0 (raise), other check
    modes -> level 1 (warn)."""
    if not checker_config.enable:
        return
    if checker_config.debug_mode in (DebugMode.DUMP_ALL, DebugMode.DUMP_FAIL):
        raise NotImplementedError(
            "enable_tensor_checker: DUMP modes run through "
            "check_numerics(output_dir=...) per tensor; the global checker "
            "supports the CHECK_* modes")
    for opt, nm in ((checker_config.output_dir, "output_dir"),
                    (checker_config.checked_op_list, "checked_op_list"),
                    (checker_config.skipped_op_list, "skipped_op_list"),
                    (checker_config.debug_step, "debug_step")):
        if opt:
            raise NotImplementedError(
                f"enable_tensor_checker: {nm} is not supported — the "
                "checker scans every op output (use check_numerics for "
                "targeted dumps)")
    level = 0 if checker_config.debug_mode == \
        DebugMode.CHECK_NAN_INF_AND_ABORT else 1
    _flags.set_flags({"check_nan_inf": True, "check_nan_inf_level": level})


def disable_tensor_checker():
    # restore the abort default so a later bare check (e.g.
    # @check_layer_numerics) raises rather than inheriting warn-only
    _flags.set_flags({"check_nan_inf": False, "check_nan_inf_level": 0})


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                   output_dir=None):
    """Scan one tensor; returns (stats, values) like the reference
    (amp/debugging.py:361): stats is the int64 [num_nan, num_inf, num_zero]
    tensor, values is the float [max, min, mean] tensor of the input. ABORT
    mode raises on a hit; DUMP modes write the tensor as .npy into
    output_dir for compare_accuracy."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    # float detection on the JAX dtype: np.issubdtype is False for
    # ml_dtypes.bfloat16 — the TPU AMP dtype this module exists to debug
    is_float = jnp.issubdtype(arr.dtype, jnp.inexact)
    a = np.asarray(arr)
    if is_float:
        num_nan = int(np.isnan(a).sum())
        num_inf = int(np.isinf(a).sum())
    else:
        num_nan = num_inf = 0
    num_zero = int((a == 0).sum())
    hit = num_nan > 0 or num_inf > 0
    if output_dir and (debug_mode == DebugMode.DUMP_ALL
                       or (hit and debug_mode == DebugMode.DUMP_FAIL)):
        os.makedirs(output_dir, exist_ok=True)
        fname = f"{op_type or 'op'}__{var_name or 'var'}.npy"
        np.save(os.path.join(output_dir, fname), a)
    if hit and debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise RuntimeError(
            f"check_numerics: {op_type or 'tensor'}:{var_name or ''} has "
            f"{num_nan} NaN / {num_inf} Inf values")
    stats = (num_nan, num_inf, num_zero)
    if a.size == 0 or num_nan == a.size:
        values = np.full(3, np.nan, np.float32)
    else:
        # np.nanmean silently skips NaN-masking for dtypes numpy doesn't
        # consider inexact (ml_dtypes.bfloat16) — cast those up first
        am = a if np.issubdtype(a.dtype, np.inexact) or not is_float \
            else a.astype(np.float32)
        with np.errstate(invalid="ignore"):
            values = np.asarray(
                [np.nanmax(am), np.nanmin(am),
                 np.nanmean(am, dtype=np.float64)], np.float32)
    return (Tensor(jnp.asarray(np.asarray(stats, np.int64))),
            Tensor(jnp.asarray(values)))


# -- operator stats ---------------------------------------------------------

_op_stats: dict | None = None
_nesting = 0


def _observer(name, arrs):
    # receives POST-autocast arrays: dtypes reflect actual run precision
    dtypes = sorted({str(a.dtype) for a in arrs
                     if hasattr(a, "dtype")}) or ["-"]
    key = (name, ",".join(dtypes))
    _op_stats[key] = _op_stats.get(key, 0) + 1


def enable_operator_stats_collection():
    """reference: amp/debugging.py — count op calls per dtype via the
    dispatcher's observer hook (core.execute consults it on every op; a
    monkeypatch would miss call sites that from-imported execute).
    Re-entrant: nested enables share one counter and only the outermost
    disable finalizes.

    Compiled-code scope (documented contract, r3 advisor weak #6): the
    observer sees ops at Python dispatch time. Under `to_static`/jit, the
    body's ops are counted ONCE — at trace time — and cache-hit replays
    of the compiled program are invisible (one additional "to_static"
    entry per call). Op-level dtype auditing of a compiled step should be
    done eagerly first, or via the XLA-level profiler. Guarded by
    tests/test_longtail_misc.py::test_op_stats_under_jit_counts_trace_once.
    """
    global _op_stats, _nesting
    from ..framework import core as _core
    if _nesting == 0:
        if _core._op_observer_hook is not None:
            raise RuntimeError(
                "another operator observer is already installed")
        _op_stats = {}
        _core._op_observer_hook = _observer
    _nesting += 1


def disable_operator_stats_collection():
    """Stop counting and print the summary table (reference prints the
    low/high-precision op table on disable)."""
    global _op_stats, _nesting
    from ..framework import core as _core
    if _nesting == 0:
        return {}
    _nesting -= 1
    if _nesting > 0:
        return dict(_op_stats or {})
    _core._op_observer_hook = None
    stats = dict(_op_stats or {})
    _op_stats = None
    if stats:
        width = max(len(k[0]) for k in stats)
        print(f"{'op':<{width}}  dtypes            calls")
        for (name, dts), n in sorted(stats.items()):
            print(f"{name:<{width}}  {dts:<16}  {n}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    """reference: amp/debugging.py collect_operator_stats context."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Diff two check_numerics dump directories (.npy per op/var) into a
    CSV report. reference: amp/debugging.py compare_accuracy /
    accuracy_compare.py (there: two run logs; here: two dump dirs)."""
    rows = []

    def _ls(p):
        return set(os.listdir(p)) if os.path.isdir(p) else set()

    names = sorted(_ls(dump_path) | _ls(another_dump_path))
    for fname in names:
        if not fname.endswith(".npy"):
            continue
        pa = os.path.join(dump_path, fname)
        pb = os.path.join(another_dump_path, fname)
        if not (os.path.exists(pa) and os.path.exists(pb)):
            rows.append((fname, "missing", "", ""))
            continue
        a, b = np.load(pa), np.load(pb)
        if a.shape != b.shape:
            rows.append((fname, "shape-mismatch", str(a.shape), str(b.shape)))
            continue
        diff = np.abs(a.astype(np.float64) - b.astype(np.float64))
        rows.append((fname, "ok", f"{diff.max():.6e}", f"{diff.mean():.6e}"))
    with open(output_filename, "w") as f:
        f.write("tensor,status,max_abs_diff,mean_abs_diff\n")
        for r in rows:
            f.write(",".join(r) + "\n")
    return rows


def check_layer_numerics(func):
    """Decorator: run a layer forward with the tensor checker enabled.
    reference: amp/debugging.py check_layer_numerics."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prev = _flags.flag_value("check_nan_inf")
        _flags.set_flags({"check_nan_inf": True})
        try:
            return func(*args, **kwargs)
        finally:
            _flags.set_flags({"check_nan_inf": prev})

    return wrapper
