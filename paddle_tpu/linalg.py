"""paddle.linalg namespace. reference: python/paddle/linalg.py — re-exports
the linear-algebra op surface plus a few linalg-only ops defined here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.core import Tensor, execute
from .tensor.linalg import (  # noqa: F401
    cholesky, norm, cond, inv, eig, eigvals, multi_dot, matrix_rank, svd,
    qr, householder_product, lu, lu_unpack, matrix_power, det, slogdet,
    eigh, eigvalsh, pinv, solve, cholesky_solve, triangular_solve, lstsq,
    svdvals, cov, corrcoef, pca_lowrank,
)

__all__ = [
    "cholesky", "cholesky_inverse", "norm", "matrix_norm", "vector_norm",
    "cond", "cov", "corrcoef", "inv", "eig", "eigvals", "multi_dot",
    "matrix_rank", "svd", "qr", "householder_product", "pca_lowrank",
    "svd_lowrank", "lu", "lu_unpack", "matrix_exp", "matrix_power", "det",
    "slogdet", "eigh", "eigvalsh", "pinv", "solve", "cholesky_solve",
    "triangular_solve", "lstsq", "ormqr",
]


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of an SPD matrix given its Cholesky factor.
    reference: linalg cholesky_inverse."""
    def f(l):
        eye = jnp.eye(l.shape[-1], dtype=l.dtype)
        li = jax.scipy.linalg.solve_triangular(l, eye, lower=not upper)
        return li.T @ li if not upper else li @ li.T
    return execute(f, x, _name="cholesky_inverse")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """reference: linalg.matrix_norm."""
    def f(a):
        if p == "fro":
            return jnp.sqrt(jnp.sum(
                jnp.abs(a) ** 2, axis=axis, keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            out = jnp.sum(s, -1)
            return out[..., None, None] if keepdim else out
        if p in (1, -1):
            colsums = jnp.sum(jnp.abs(a), axis=axis[0], keepdims=True)
            red = jnp.max if p == 1 else jnp.min
            out = red(colsums, axis=axis[1], keepdims=True)
            return out if keepdim else jnp.squeeze(out, axis)
        if p in (2, -2):
            s = jnp.linalg.svd(a, compute_uv=False)
            out = (jnp.max if p == 2 else jnp.min)(s, -1)
            return out[..., None, None] if keepdim else out
        if p in (float("inf"), float("-inf")):
            rowsums = jnp.sum(jnp.abs(a), axis=axis[1], keepdims=True)
            red = jnp.max if p == float("inf") else jnp.min
            out = red(rowsums, axis=axis[0], keepdims=True)
            return out if keepdim else jnp.squeeze(out, axis)
        raise ValueError(f"unsupported matrix norm order {p!r}")
    return execute(f, x, _name="matrix_norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """reference: linalg.vector_norm."""
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax,
                       keepdims=keepdim) ** (1.0 / p)
    return execute(f, x, _name="vector_norm")


def matrix_exp(x, name=None):
    """reference: linalg.matrix_exp (Pade approximation in the reference;
    jax.scipy implements the same scaling-and-squaring algorithm)."""
    return execute(jax.scipy.linalg.expm, x, _name="matrix_exp")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (Halko et al.), like the reference's
    svd_lowrank: subspace iteration on a Gaussian sketch."""
    from .framework.random import next_key
    key = next_key()
    args = [x] + ([M] if M is not None else [])

    def f(a, *rest):
        am = a - rest[0] if rest else a
        m, n = am.shape[-2:]
        k = min(q, m, n)
        omega = jax.random.normal(key, am.shape[:-2] + (n, k), am.dtype)
        y = am @ omega
        for _ in range(niter):
            y = am @ (jnp.swapaxes(am, -1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -1, -2) @ am
        u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_b, s, jnp.swapaxes(vh, -1, -2)
    return execute(f, *args, _name="svd_lowrank")


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by the orthogonal Q of a geqrf factorization
    (x householder vectors + tau). reference: linalg.ormqr."""
    def f(a, t, c):
        def one(a2, t1, c2):
            q = _householder_q(a2, t1)
            if transpose:
                q = q.T
            return q @ c2 if left else c2 @ q
        fn = one
        for _ in range(a.ndim - 2):  # map over leading batch dims
            fn = jax.vmap(fn)
        return fn(a, t, c)
    return execute(f, x, tau, other, _name="ormqr")


def _householder_q(a, tau):
    m, k = a.shape[-2], tau.shape[-1]
    q = jnp.eye(m, dtype=a.dtype)
    for i in range(k):
        v = jnp.zeros((m,), a.dtype).at[i].set(1.0)
        v = v.at[i + 1:].set(a[..., i + 1:, i])
        h = jnp.eye(m, dtype=a.dtype) - tau[..., i] * jnp.outer(v, v)
        q = q @ h
    return q


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, output_dtype="float16",
                            scale=1.0, activation_type="identity", name=None):
    """fp8 x fp8 -> half GEMM. reference: linalg.fp8_fp8_half_gemm_fused
    (cuBLASLt). On TPU fp8 operands feed the MXU natively via XLA."""
    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b, preferred_element_type=jnp.float32) * scale
        if rest:
            out = out + rest[0].astype(out.dtype)
        if activation_type in ("gelu",):
            out = jax.nn.gelu(out)
        elif activation_type in ("relu",):
            out = jax.nn.relu(out)
        from .framework import dtypes as _dt
        return out.astype(_dt.convert_dtype(output_dtype))
    args = [x, y] + ([bias] if bias is not None else [])
    return execute(f, *args, _name="fp8_gemm")
