"""Tensor creation ops. reference: python/paddle/tensor/creation.py."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dt
from ..framework.core import Tensor, execute, to_tensor  # noqa: F401

__all__ = [
    "to_tensor", "zeros", "zeros_like", "ones", "ones_like", "full",
    "full_like", "arange", "linspace", "logspace", "eye", "empty",
    "empty_like", "tril", "triu", "diag", "diagflat", "meshgrid",
    "assign", "clone", "tril_indices", "triu_indices", "one_hot",
    "complex", "polar",
]


def _dtype(dtype, default=None):
    if dtype is None:
        return default if default is not None else _dt.convert_dtype(_dt.get_default_dtype())
    return _dt.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = _dt.convert_dtype("bool") if isinstance(fill_value, bool) else _dtype(None)
    else:
        dtype = _dtype(dtype)
    return Tensor(jnp.full(_shape(shape), fill_value, dtype))


def zeros_like(x, dtype=None, name=None):
    return execute(lambda a: jnp.zeros_like(a, dtype=_dt.convert_dtype(dtype)), x, _name="zeros_like") if isinstance(x, Tensor) else Tensor(jnp.zeros_like(jnp.asarray(x), dtype=_dt.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else jnp.asarray(x), dtype=_dt.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else jnp.asarray(x), fill_value, dtype=_dt.convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = jnp.int64 if all(isinstance(v, (int, np.integer)) for v in (start, end, step)) else _dtype(None)
    else:
        dtype = _dt.convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(_f(start), _f(stop), int(_f(num)), dtype=_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(_f(start), _f(stop), int(_f(num)), base=_f(base), dtype=_dtype(dtype)))


def _f(x):
    return x.item() if isinstance(x, Tensor) else x


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dtype(dtype)))


def tril(x, diagonal=0, name=None):
    return execute(lambda a: jnp.tril(a, diagonal), x, _name="tril")


def triu(x, diagonal=0, name=None):
    return execute(lambda a: jnp.triu(a, diagonal), x, _name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1:
            d = jnp.diag(a, offset)
            if padding_value != 0:
                n = a.shape[0] + abs(offset)
                mask = jnp.eye(n, k=offset, dtype=bool)
                d = jnp.where(mask, d, padding_value)
            return d
        return jnp.diagonal(a, offset)
    return execute(f, x, _name="diag")


def diagflat(x, offset=0, name=None):
    return execute(lambda a: jnp.diagflat(a, offset), x, _name="diagflat")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    outs = execute(lambda *arrs: tuple(jnp.meshgrid(*arrs, indexing="ij")), *args, _name="meshgrid")
    return list(outs)


def assign(x, output=None):
    src = Tensor(jnp.asarray(x._data if isinstance(x, Tensor) else np.asarray(x)))
    if output is not None:
        output.set_value(src)
        return output
    return src


def clone(x, name=None):
    return x.clone()


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt.convert_dtype(dtype)))


def one_hot(x, num_classes, name=None):
    import jax.nn as jnn
    return execute(lambda a: jnn.one_hot(a, num_classes, dtype=_dtype(None)), x, _name="one_hot")


def complex(real, imag, name=None):
    return execute(lambda r, i: jax.lax.complex(r, i), real, imag, _name="complex")


def polar(abs_, angle, name=None):
    return execute(lambda a, t: a * jnp.exp(1j * t.astype(jnp.complex64)), abs_, angle, _name="polar")


import jax  # noqa: E402  (used by complex)


def create_tensor(dtype, name=None, persistable=False):
    """reference: python/paddle/tensor/creation.py create_tensor — an empty
    typed Tensor to be assign()ed into."""
    from ..framework.dtypes import convert_dtype
    return Tensor(jnp.zeros((0,), convert_dtype(dtype)))


__all__.append("create_tensor")
