"""Linear algebra. reference: python/paddle/tensor/linalg.py.

Decompositions route to jax.numpy.linalg / jax.scipy.linalg (XLA custom
calls), replacing the reference's cuSOLVER/LAPACK dynload kernels
(paddle/phi/kernels/gpu/*svd*, *eig*, funcs/blas/)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute
from .math import matmul, mm, bmm, dot  # noqa: F401 re-export

__all__ = [
    "matmul", "mm", "bmm", "dot", "t", "transpose_last2", "norm", "dist",
    "cond", "matrix_power", "matrix_rank", "det", "slogdet", "inv", "pinv",
    "solve", "triangular_solve", "cholesky", "cholesky_solve", "lu",
    "lu_unpack", "qr", "svd", "svdvals", "eig", "eigvals", "eigh",
    "eigvalsh", "lstsq", "multi_dot", "cross", "histogram", "histogramdd",
    "bincount", "mv", "corrcoef", "cov", "matrix_transpose", "householder_product",
    "pca_lowrank", "vecdot", "tensordot",
]


def t(x, name=None):
    return execute(lambda a: a.T if a.ndim <= 2 else a, x, _name="t")


def transpose_last2(x, name=None):
    return execute(lambda a: jnp.swapaxes(a, -1, -2), x, _name="transpose_last2")


matrix_transpose = transpose_last2


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None and p is None:
            return jnp.linalg.norm(a.reshape(-1), 2)
        if axis is None:
            if p == "fro":   # Frobenius over the whole tensor == flat 2-norm
                return jnp.linalg.norm(a.reshape(-1), 2)
            if p == "nuc":   # nuclear norm needs the matrix form
                return jnp.linalg.norm(a, "nuc")
            return jnp.linalg.norm(a.reshape(-1), _p(p))
        if isinstance(axis, (list, tuple)) and len(axis) == 2:
            return jnp.linalg.norm(a, _p(p) if p is not None else "fro", axis=tuple(axis), keepdims=keepdim)
        return jnp.linalg.norm(a, _p(p) if p is not None else 2, axis=axis if not isinstance(axis, (list, tuple)) else axis[0], keepdims=keepdim)
    return execute(f, x, _name="norm")


def _p(p):
    if p == "fro":
        return "fro"
    if p == "nuc":
        return "nuc"
    if p == float("inf") or p == "inf":
        return jnp.inf
    if p == float("-inf"):
        return -jnp.inf
    return p


def dist(x, y, p=2, name=None):
    return execute(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), _p(p)), x, y, _name="dist")


def cond(x, p=None, name=None):
    return execute(lambda a: jnp.linalg.cond(a, _p(p)), x, _name="cond")


def matrix_power(x, n, name=None):
    return execute(lambda a: jnp.linalg.matrix_power(a, n), x, _name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return execute(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x, _name="matrix_rank")


def det(x, name=None):
    return execute(jnp.linalg.det, x, _name="det")


def slogdet(x, name=None):
    def f(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l]) if s.ndim == 0 else jnp.stack([s, l])
    return execute(f, x, _name="slogdet")


def inv(x, name=None):
    return execute(jnp.linalg.inv, x, _name="inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return execute(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x, _name="pinv")


def solve(x, y, name=None):
    return execute(jnp.linalg.solve, x, y, _name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return execute(f, x, y, _name="triangular_solve")


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return execute(f, x, _name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return execute(f, x, y, _name="cholesky_solve")


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    lu_t, piv_t = execute(f, x, _name="lu")
    if get_infos:
        return lu_t, piv_t, Tensor(jnp.zeros((), jnp.int32))
    return lu_t, piv_t


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def f(lu_, piv):
        m = lu_.shape[-2]
        l = jnp.tril(lu_, -1) + jnp.eye(m, lu_.shape[-1], dtype=lu_.dtype)
        l = l[..., :, :min(lu_.shape[-2:])] if False else jnp.tril(lu_, -1)[..., :, :] + jnp.eye(lu_.shape[-2], lu_.shape[-1], dtype=lu_.dtype)
        u = jnp.triu(lu_)
        # build permutation matrix from pivots (1-based sequential swaps)
        def body(i, perm):
            j = piv[i] - 1
            pi = perm[i]
            pj = perm[j]
            perm = perm.at[i].set(pj)
            perm = perm.at[j].set(pi)
            return perm
        perm = jnp.arange(m)
        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        p = jnp.eye(m, dtype=lu_.dtype)[perm].T
        return p, l, u
    return execute(f, x, y, _name="lu_unpack")


def qr(x, mode="reduced", name=None):
    def f(a):
        return jnp.linalg.qr(a, mode=mode)
    if mode == "r":
        return execute(lambda a: jnp.linalg.qr(a, mode="r"), x, _name="qr")
    q, r = execute(f, x, _name="qr")
    return q, r


def svd(x, full_matrices=False, name=None):
    def f(a):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()  # paddle returns V not V^H
    return execute(f, x, _name="svd")


def svdvals(x, name=None):
    return execute(lambda a: jnp.linalg.svd(a, compute_uv=False), x, _name="svdvals")


def eig(x, name=None):
    return execute(lambda a: jnp.linalg.eig(a), x, _name="eig")


def eigvals(x, name=None):
    return execute(jnp.linalg.eigvals, x, _name="eigvals")


def eigh(x, UPLO="L", name=None):
    return execute(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), x, _name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return execute(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x, _name="eigvalsh")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return execute(f, x, y, _name="lstsq")


def multi_dot(x, name=None):
    return execute(lambda *arrs: jnp.linalg.multi_dot(arrs), *x, _name="multi_dot")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            for i, s in enumerate(a.shape):
                if s == 3:
                    ax = i
                    break
        return jnp.cross(a, b, axis=ax)
    return execute(f, x, y, _name="cross")


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def f(a, w=None):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi), weights=w, density=density)
        return h if density or w is not None else h.astype(jnp.int64)
    if weight is not None:
        return execute(f, input, weight, _name="histogram")
    return execute(f, input, _name="histogram")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    # reference contract (tensor/linalg.py:5321): `ranges` is a FLAT
    # sequence [lo0, hi0, lo1, hi1, ...]; jnp wants per-dim pairs
    pair_ranges = None
    if ranges is not None:
        flat = list(ranges)
        pair_ranges = [tuple(flat[i:i + 2]) for i in range(0, len(flat), 2)]

    def f(a, w=None):
        h, edges = jnp.histogramdd(a, bins=bins, range=pair_ranges,
                                   density=density, weights=w)
        return (h,) + tuple(edges)
    outs = execute(f, x, *( [weights] if weights is not None else []), _name="histogramdd")
    return outs[0], list(outs[1:])


def bincount(x, weights=None, minlength=0, name=None):
    import numpy as np
    length = builtins_max(minlength, int(np.asarray(x._data).max()) + 1 if x.size else 0)
    def f(a, w=None):
        return jnp.bincount(a, w, length=length)
    if weights is not None:
        return execute(f, x, weights, _name="bincount")
    return execute(f, x, _name="bincount")


import builtins


def builtins_max(*a):
    return builtins.max(*a)


def mv(x, vec, name=None):
    return execute(lambda a, v: a @ v, x, vec, _name="mv")


def corrcoef(x, rowvar=True, name=None):
    return execute(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, _name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def f(a, *rest):
        fw = rest[0] if fweights is not None else None
        aw = rest[len([r for r in [fweights] if r is not None])] if aweights is not None else None
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw)
    args = [x] + [w for w in (fweights, aweights) if w is not None]
    return execute(f, *args, _name="cov")


def householder_product(x, tau, name=None):
    def f(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        def make_q(acol, tval):
            pass
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q
        for i in range(t_.shape[-1]):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0) if v.ndim == 1 else jnp.concatenate([v[..., :i] * 0, jnp.ones_like(v[..., i:i+1]), v[..., i+1:]], axis=-1)
            ti = t_[..., i]
            outer_ = v[..., :, None] * v[..., None, :]
            h = jnp.eye(m, dtype=a.dtype) - ti[..., None, None] * outer_
            q = q @ h
        return q[..., :, :n]
    return execute(f, x, tau, _name="householder_product")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def f(a):
        qq = q if q is not None else min(6, a.shape[-2], a.shape[-1])
        b = a - a.mean(axis=-2, keepdims=True) if center else a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vh, -1, -2)[..., :qq]
    return execute(f, x, _name="pca_lowrank")


def vecdot(x, y, axis=-1, name=None):
    return execute(lambda a, b: jnp.sum(a * b, axis=axis), x, y, _name="vecdot")


def tensordot(x, y, axes=2, name=None):
    def conv_axes(ax):
        if isinstance(ax, Tensor):
            import numpy as np
            ax = np.asarray(ax._data).tolist()
        if isinstance(ax, (list, tuple)):
            return tuple(conv_axes(a) for a in ax) if isinstance(ax[0], (list, tuple, Tensor)) else tuple(int(a) for a in ax)
        return int(ax) if not isinstance(ax, int) else ax
    ax = conv_axes(axes)
    return execute(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y, _name="tensordot")


def inverse(x, name=None):
    """Alias of linalg.inv (reference: paddle.inverse / tensor method)."""
    return inv(x, name=name)


__all__.append("inverse")
