"""Search/sort ops. reference: python/paddle/tensor/search.py.

top_k lowers to jax.lax.top_k (TPU-optimized); sort to XLA's variadic sort.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dt
from ..framework.core import Tensor, execute

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "top_k", "searchsorted",
    "index_sample", "masked_select", "nonzero", "where", "mode", "kthvalue",
    "unique", "unique_consecutive", "bucketize",
]

from .manipulation import index_sample, masked_select, nonzero, where  # noqa: F401


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        if axis is None:
            r = jnp.argmax(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        r = jnp.argmax(a, axis=axis)
        return jnp.expand_dims(r, axis) if keepdim else r
    out = execute(f, x, _name="argmax")
    return out.astype(dtype) if dtype else out


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        if axis is None:
            r = jnp.argmin(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        r = jnp.argmin(a, axis=axis)
        return jnp.expand_dims(r, axis) if keepdim else r
    out = execute(f, x, _name="argmin")
    return out.astype(dtype) if dtype else out


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)
    return execute(f, x, _name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return s
    return execute(f, x, _name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k._data) if isinstance(k, Tensor) else int(k)
    def f(a):
        ax = a.ndim - 1 if axis is None else axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)
    return execute(f, x, _name="topk")


top_k = topk


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            r = jnp.searchsorted(seq, v, side=side)
        else:
            r = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return r.astype(jnp.int32 if out_int32 else jnp.int64)
    return execute(f, sorted_sequence, values, _name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def f(a, seq):
        r = jnp.searchsorted(seq, a, side="right" if right else "left")
        return r.astype(jnp.int32 if out_int32 else jnp.int64)
    return execute(f, x, sorted_sequence, _name="bucketize")


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        n = moved.shape[-1]
        s = jnp.sort(moved, axis=-1)
        si = jnp.argsort(moved, axis=-1, stable=True)
        # count runs in sorted order; mode = value with max count (last occurrence)
        eq = s[..., 1:] == s[..., :-1]
        runid = jnp.concatenate([jnp.zeros_like(s[..., :1], dtype=jnp.int32),
                                 jnp.cumsum((~eq).astype(jnp.int32), -1)], -1)
        counts = jax.vmap(lambda r: jnp.bincount(r, length=n))(runid.reshape(-1, n)).reshape(runid.shape[:-1] + (n,))
        cnt_per_elem = jnp.take_along_axis(counts, runid, axis=-1)
        best = jnp.argmax(cnt_per_elem + jnp.arange(n) * 1e-9, axis=-1)
        vals = jnp.take_along_axis(s, best[..., None], -1)[..., 0]
        idxs = jnp.take_along_axis(si, best[..., None], -1)[..., 0].astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idxs = jnp.expand_dims(idxs, ax)
        else:
            vals = jnp.moveaxis(vals[..., None], -1, ax)[..., 0] if False else vals
            idxs = idxs
        return vals, idxs
    return execute(f, x, _name="mode")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        s = jnp.sort(a, axis=ax)
        si = jnp.argsort(a, axis=ax, stable=True)
        vals = jnp.take(s, k - 1, axis=ax)
        idxs = jnp.take(si, k - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idxs = jnp.expand_dims(idxs, ax)
        return vals, idxs
    return execute(f, x, _name="kthvalue")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic output size → host computation (non-jittable, like reference's
    # unique CPU fallback for dynamic shapes)
    a = np.asarray(x._data)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r if i == 0 else r.astype(np.int64)))
            for i, r in enumerate(res)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(x._data)
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
    else:
        diff = (a.take(range(1, a.shape[axis]), axis) != a.take(range(0, a.shape[axis] - 1), axis))
        keep = np.concatenate([[True], diff.reshape(diff.shape[axis] if diff.ndim == 1 else -1, *([] if diff.ndim == 1 else [])).any(axis=tuple(i for i in range(diff.ndim) if i != axis)) if diff.ndim > 1 else diff])
    vals = a[keep] if axis is None else np.compress(keep, a, axis)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        n = a.shape[0] if axis is None else a.shape[axis]
        counts = np.diff(np.append(idx, n))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling over probability rows.

    reference: python/paddle/tensor/search.py:1363 top_p_sampling (backed by
    the top_p_sampling CUDA kernel, ops.yaml). x: (batch, vocab)
    probabilities; ps: (batch,) per-row cumulative-probability cutoffs;
    threshold: (batch,) minimum sampleable score; topp_seed: (batch,) int64
    per-row seeds; mode 'truncated' restricts sampling to the nucleus,
    'non-truncated' samples the full (threshold-filtered) distribution.
    Returns (value, id), each (batch, 1); with return_top also the top-k
    scores and ids.

    TPU-native: sort + cumsum + renormalize + categorical draw — all dense
    XLA ops; the reference's fused kernel exists to avoid the full-vocab
    sort on GPU, which XLA handles fine on TPU.
    """
    import jax
    from ..framework.random import next_key

    if mode not in ("truncated", "non-truncated"):
        raise ValueError(f"mode must be 'truncated' or 'non-truncated', "
                         f"got {mode!r}")

    def f(probs, p, *extra):
        it = iter(extra)
        thr = next(it) if threshold is not None else None
        row_seeds = next(it) if topp_seed is not None else None
        filt_src = probs
        if thr is not None:
            filt_src = jnp.where(probs >= thr[..., None], probs, 0.0)
        sort_idx = jnp.argsort(-filt_src, axis=-1)
        sorted_p = jnp.take_along_axis(filt_src, sort_idx, axis=-1)
        if mode == "truncated":
            cum = jnp.cumsum(sorted_p, axis=-1)
            # keep tokens whose PRECEDING mass is < p (first always kept)
            keep = (cum - sorted_p) < p[..., None]
            filt = jnp.where(keep, sorted_p, 0.0)
        else:
            filt = sorted_p
        logits = jnp.log(jnp.maximum(filt, 1e-30))
        if row_seeds is not None:
            keys = jax.vmap(jax.random.key)(row_seeds.astype(jnp.uint32))
            pos = jax.vmap(
                lambda kk, lg: jax.random.categorical(kk, lg))(keys, logits)
        else:
            key = next_key() if seed < 0 else jax.random.key(seed)
            pos = jax.random.categorical(key, logits, axis=-1)
        idx = jnp.take_along_axis(sort_idx, pos[..., None], axis=-1)
        val = jnp.take_along_axis(probs, idx, axis=-1)
        outs = (val, idx.astype(jnp.int64))
        if return_top:
            kk = k if k > 0 else 1
            top_scores, top_ids = jax.lax.top_k(probs, kk)
            outs = outs + (top_scores, top_ids.astype(jnp.int64))
        return outs

    args = (x, ps)
    if threshold is not None:
        args += (threshold,)
    if topp_seed is not None:
        args += (topp_seed,)
    return execute(f, *args, _name="top_p_sampling")


__all__.append("top_p_sampling")
