"""Random ops on the global PRNG. reference: python/paddle/tensor/random.py.

Paddle's stateful generators map onto a host-side counter folded into a jax
PRNG key (framework/random.py) — deterministic per seed, trace-safe under
jit.to_static (key is a traced input there).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtypes as _dt
from ..framework.core import Tensor, execute
from ..framework.random import next_key

__all__ = [
    "rand", "randn", "standard_normal", "normal", "normal_", "uniform",
    "uniform_", "randint", "randint_like", "randperm", "bernoulli",
    "poisson", "multinomial", "standard_gamma", "binomial", "exponential_",
    "gumbel_softmax", "log_normal", "log_normal_", "bernoulli_",
    "cauchy_", "geometric_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        import numpy as np
        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def _dtype(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None else _dt.convert_dtype(_dt.get_default_dtype())


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dtype(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dtype(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        def f(*args):
            i = 0
            m = args[i] if isinstance(mean, Tensor) else mean
            if isinstance(mean, Tensor):
                i += 1
            s = args[i] if isinstance(std, Tensor) else std
            shp = jnp.broadcast_shapes(
                m.shape if hasattr(m, "shape") else (),
                s.shape if hasattr(s, "shape") else ())
            return m + s * jax.random.normal(next_key(), shp, _dtype(None))
        args = [a for a in (mean, std) if isinstance(a, Tensor)]
        return execute(f, *args, _name="normal")
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape or [1]), _dtype(None)))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = mean + std * jax.random.normal(next_key(), x._data.shape, x._data.dtype)
    return x


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dtype(dtype), minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    x._data = jax.random.uniform(key, x._data.shape, x._data.dtype, minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high, _dt.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = _dt.convert_dtype(dtype) if dtype else x._data.dtype
    return Tensor(jax.random.randint(next_key(), x._data.shape, low, high, jnp.int64).astype(dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(_dt.convert_dtype(dtype)))


def bernoulli(x, p=None, name=None):
    def f(a):
        return jax.random.bernoulli(next_key(), a if p is None else p, a.shape).astype(a.dtype)
    return execute(f, x, _name="bernoulli")


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(next_key(), p, x._data.shape).astype(x._data.dtype)
    return x


def poisson(x, name=None):
    def f(a):
        return jax.random.poisson(next_key(), a).astype(a.dtype)
    return execute(f, x, _name="poisson")


def multinomial(x, num_samples=1, replacement=False, name=None):
    def f(a):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if replacement:
            return jax.random.categorical(next_key(), logits, axis=-1,
                                          shape=(num_samples,) + a.shape[:-1]).T if a.ndim > 1 else \
                   jax.random.categorical(next_key(), logits, axis=-1, shape=(num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(next_key(), a.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    out = execute(f, x, _name="multinomial")
    return out.astype("int64")


def standard_gamma(x, name=None):
    def f(a):
        return jax.random.gamma(next_key(), a)
    return execute(f, x, _name="standard_gamma")


def binomial(count, prob, name=None):
    def f(n, p):
        return jax.random.binomial(next_key(), n.astype(jnp.float32), p).astype(jnp.int64)
    return execute(f, count, prob, _name="binomial")


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(next_key(), x._data.shape, x._data.dtype) / lam).astype(x._data.dtype)
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return Tensor(jnp.exp(mean + std * jax.random.normal(next_key(), _shape(shape or [1]), _dtype(None))))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """In-place lognormal fill. reference: tensor/random.py log_normal_."""
    x._data = jnp.exp(mean + std * jax.random.normal(
        next_key(), x._data.shape)).astype(x._data.dtype)
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    x._data = (loc + scale * jax.random.cauchy(next_key(), x._data.shape, x._data.dtype)).astype(x._data.dtype)
    return x


def geometric_(x, probs=0.5, name=None):
    u = jax.random.uniform(next_key(), x._data.shape)
    x._data = (jnp.floor(jnp.log1p(-u) / jnp.log1p(-probs)) + 1).astype(x._data.dtype)
    return x


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    def f(a):
        g = jax.random.gumbel(next_key(), a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.put_along_axis(jnp.zeros_like(y), idx, 1.0, axis=axis, inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y  # straight-through
        return y
    return execute(f, x, _name="gumbel_softmax")
