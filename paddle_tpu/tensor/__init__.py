"""Tensor op surface + method patching.

reference: python/paddle/tensor/__init__.py plus the monkey-patch machinery in
python/paddle/base/dygraph/math_op_patch.py and tensor_patch_methods.py — every
free function `paddle.foo(x)` is also available as `x.foo()`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, execute
from . import (attribute, creation, einsum, linalg, logic, manipulation, math,
               random, search, stat)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .attribute import rank, shape as shape_op, is_complex, is_floating_point, is_integer  # noqa: F401


# ---------------------------------------------------------------------------
# operator overloads (math_op_patch)
# ---------------------------------------------------------------------------

def _binop(f, reverse=False):
    def op(self, other):
        if reverse:
            return execute(lambda b, a: f(a, b), self, other)
        return execute(f, self, other)
    return op


Tensor.__add__ = _binop(jnp.add)
Tensor.__radd__ = _binop(jnp.add, reverse=True)
Tensor.__sub__ = _binop(jnp.subtract)
Tensor.__rsub__ = _binop(jnp.subtract, reverse=True)
Tensor.__mul__ = _binop(jnp.multiply)
Tensor.__rmul__ = _binop(jnp.multiply, reverse=True)
Tensor.__truediv__ = _binop(jnp.true_divide)
Tensor.__rtruediv__ = _binop(jnp.true_divide, reverse=True)
Tensor.__floordiv__ = _binop(jnp.floor_divide)
Tensor.__rfloordiv__ = _binop(jnp.floor_divide, reverse=True)
Tensor.__mod__ = _binop(jnp.mod)
Tensor.__rmod__ = _binop(jnp.mod, reverse=True)
Tensor.__pow__ = _binop(jnp.power)
Tensor.__rpow__ = _binop(jnp.power, reverse=True)
Tensor.__matmul__ = _binop(jnp.matmul)
Tensor.__rmatmul__ = _binop(jnp.matmul, reverse=True)
Tensor.__neg__ = lambda self: execute(jnp.negative, self)
Tensor.__abs__ = lambda self: execute(jnp.abs, self)
Tensor.__invert__ = lambda self: execute(jnp.logical_not if self.dtype == jnp.bool_ else jnp.bitwise_not, self)
Tensor.__eq__ = _binop(jnp.equal)
Tensor.__ne__ = _binop(jnp.not_equal)
Tensor.__lt__ = _binop(jnp.less)
Tensor.__le__ = _binop(jnp.less_equal)
Tensor.__gt__ = _binop(jnp.greater)
Tensor.__ge__ = _binop(jnp.greater_equal)
Tensor.__and__ = _binop(jnp.bitwise_and)
Tensor.__or__ = _binop(jnp.bitwise_or)
Tensor.__xor__ = _binop(jnp.bitwise_xor)
Tensor.__lshift__ = _binop(jnp.left_shift)
Tensor.__rshift__ = _binop(jnp.right_shift)
Tensor.__hash__ = object.__hash__  # __eq__ override killed it; identity hash


# ---------------------------------------------------------------------------
# method attachment: x.foo(...) == paddle.foo(x, ...)
# ---------------------------------------------------------------------------

_METHOD_MODULES = [math, manipulation, linalg, logic, search, stat, creation, attribute]
_SKIP = {"to_tensor", "zeros", "ones", "full", "arange", "linspace", "eye",
         "empty", "meshgrid", "tril_indices", "triu_indices", "where",
         "einsum", "multi_dot", "broadcast_tensors", "scatter_nd",
         "hstack", "vstack", "dstack", "column_stack", "row_stack",
         "atleast_1d", "atleast_2d", "atleast_3d"}


def _attach():
    for mod in _METHOD_MODULES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or name.startswith("_"):
                continue
            fn = getattr(mod, name, None)
            if fn is None or not callable(fn):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # in-place twins are generated once, below (_gen_inplace covers both
    # the module-level foo_() and the Tensor.foo_() method surface)

    # x.where(cond-style): paddle Tensor.where(x, y) means where(self_cond?..)
    Tensor.where = lambda self, x, y, name=None: manipulation.where(self, x, y)
    Tensor.mean = math.mean
    Tensor.sum = math.sum
    Tensor.max = math.max
    Tensor.min = math.min
    Tensor.matmul = math.matmul
    Tensor.mm = math.matmul
    Tensor.norm = linalg.norm
    Tensor.transpose = manipulation.transpose
    Tensor.reshape = manipulation.reshape
    Tensor.unsqueeze = manipulation.unsqueeze
    Tensor.squeeze = manipulation.squeeze


_attach()


# ---------------------------------------------------------------------------
# extras + generated in-place surface
# ---------------------------------------------------------------------------

from . import extras as _extras
from .extras import *  # noqa: F401,F403

# reference exposes an in-place twin (`foo_`) for most elementwise/layout
# ops (tensor_patch_methods + generated inplace kernels). Our tensors are
# functional underneath — in-place is a rebind of the same Python object —
# so the twins are generated, not hand-written.
_INPLACE_BASES = [
    "abs", "acos", "acosh", "addmm", "asin", "asinh", "atan", "atanh",
    "cosh", "erfinv", "lerp", "log1p", "not_equal", "put_along_axis",
    "bitwise_and", "bitwise_left_shift",
    "bitwise_not", "bitwise_or", "bitwise_right_shift", "bitwise_xor",
    "cast", "copysign", "cos", "cumprod", "cumsum", "digamma", "divide",
    "equal", "erf", "expm1", "floor_divide", "floor_mod", "frac", "gcd",
    "greater_equal", "greater_than", "hypot", "i0", "index_add",
    "index_put", "lcm", "ldexp", "less_equal", "less_than", "lgamma",
    "log", "log10", "log2", "logical_and", "logical_not", "logical_or",
    "logical_xor", "logit", "masked_fill", "mod", "multiply", "nan_to_num",
    "neg", "pow", "remainder", "scatter", "sin", "sinh", "square", "t",
    "tan", "tanh", "transpose", "tril", "triu", "trunc", "gammainc",
    "gammaincc", "gammaln", "multigammaln", "polygamma", "sinc", "renorm",
    "masked_scatter", "index_fill", "add", "subtract", "clip", "scale",
    "floor", "ceil", "exp", "sqrt", "rsqrt", "reciprocal", "round",
    "sigmoid",
]


def _gen_inplace():
    import functools
    import sys
    mod = sys.modules[__name__]
    for base in _INPLACE_BASES:
        fn = getattr(mod, base, None)
        if fn is None:
            continue
        name = base + "_"
        if getattr(mod, name, None) is not None:
            continue

        def make(f):
            @functools.wraps(f)
            def g(x, *a, **k):
                return x._rebind(f(x, *a, **k))
            g.__qualname__ = g.__name__ = f.__name__ + "_"
            return g

        g = make(fn)
        setattr(mod, name, g)
        if not hasattr(Tensor, name):
            setattr(Tensor, name, g)


_gen_inplace()


def where_(condition, x, y=None, name=None):
    """In-place where: x keeps values where condition, takes y elsewhere."""
    out = manipulation.where(condition, x, y)
    return x._rebind(out)


Tensor.where_ = lambda self, cond, y, name=None: where_(cond, self, y)


# ---------------------------------------------------------------------------
# tensor_method_func parity: the reference patches every tensor-domain free
# function onto Tensor (python/paddle/tensor/__init__.py tensor_method_func).
# The loop above covers the core modules; extras/random/signal and the
# linalg-namespace-only ops are attached here.
# ---------------------------------------------------------------------------

def _attach_more():
    for name in getattr(_extras, "__all__", []):
        fn = getattr(_extras, name, None)
        if callable(fn) and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    # random: only the tensor-first ops (in-place fillers + samplers);
    # factories like randn(shape) must not bind a tensor as their shape
    for name in getattr(random, "__all__", []):
        if not (name.endswith("_") or name in
                ("multinomial", "poisson", "binomial", "standard_gamma")):
            continue
        fn = getattr(random, name, None)
        if callable(fn) and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    # search/creation late additions (top_p_sampling, create_tensor)
    for name in ("top_p_sampling",):
        if not hasattr(Tensor, name):
            setattr(Tensor, name, getattr(search, name))
    Tensor.create_tensor = staticmethod(creation.create_tensor)
    if not hasattr(Tensor, "inverse"):
        Tensor.inverse = linalg.inverse
    # _SKIP members the reference nevertheless exposes as methods: the
    # tensor binds as the first argument (for scatter_nd that IS the index,
    # matching the reference signature scatter_nd(index, updates, shape))
    for name in ("atleast_1d", "atleast_2d", "atleast_3d",
                 "broadcast_tensors", "scatter_nd"):
        if not hasattr(Tensor, name):
            setattr(Tensor, name, getattr(manipulation, name))
    if not hasattr(Tensor, "multi_dot"):
        Tensor.multi_dot = linalg.multi_dot
    # Tensor.create_parameter is attached by the package root, where the
    # function is defined (paddle_tpu/__init__.py)

    # signal + linalg-namespace methods resolve lazily: those modules import
    # from this package, so importing them here would be circular
    def _lazy(module, name):
        def m(self, *a, **k):
            import importlib
            fn = getattr(importlib.import_module(module), name)
            return fn(self, *a, **k)
        m.__name__ = name
        return m

    for name in ("stft", "istft"):
        if not hasattr(Tensor, name):
            setattr(Tensor, name, _lazy("paddle_tpu.signal", name))
    for name in ("cholesky_inverse", "ormqr", "svd_lowrank"):
        if not hasattr(Tensor, name):
            setattr(Tensor, name, _lazy("paddle_tpu.linalg", name))


_attach_more()
