"""Tensor op surface + method patching.

reference: python/paddle/tensor/__init__.py plus the monkey-patch machinery in
python/paddle/base/dygraph/math_op_patch.py and tensor_patch_methods.py — every
free function `paddle.foo(x)` is also available as `x.foo()`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, execute
from . import (attribute, creation, einsum, linalg, logic, manipulation, math,
               random, search, stat)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .attribute import rank, shape as shape_op, is_complex, is_floating_point, is_integer  # noqa: F401


# ---------------------------------------------------------------------------
# operator overloads (math_op_patch)
# ---------------------------------------------------------------------------

def _binop(f, reverse=False):
    def op(self, other):
        if reverse:
            return execute(lambda b, a: f(a, b), self, other)
        return execute(f, self, other)
    return op


Tensor.__add__ = _binop(jnp.add)
Tensor.__radd__ = _binop(jnp.add, reverse=True)
Tensor.__sub__ = _binop(jnp.subtract)
Tensor.__rsub__ = _binop(jnp.subtract, reverse=True)
Tensor.__mul__ = _binop(jnp.multiply)
Tensor.__rmul__ = _binop(jnp.multiply, reverse=True)
Tensor.__truediv__ = _binop(jnp.true_divide)
Tensor.__rtruediv__ = _binop(jnp.true_divide, reverse=True)
Tensor.__floordiv__ = _binop(jnp.floor_divide)
Tensor.__rfloordiv__ = _binop(jnp.floor_divide, reverse=True)
Tensor.__mod__ = _binop(jnp.mod)
Tensor.__rmod__ = _binop(jnp.mod, reverse=True)
Tensor.__pow__ = _binop(jnp.power)
Tensor.__rpow__ = _binop(jnp.power, reverse=True)
Tensor.__matmul__ = _binop(jnp.matmul)
Tensor.__rmatmul__ = _binop(jnp.matmul, reverse=True)
Tensor.__neg__ = lambda self: execute(jnp.negative, self)
Tensor.__abs__ = lambda self: execute(jnp.abs, self)
Tensor.__invert__ = lambda self: execute(jnp.logical_not if self.dtype == jnp.bool_ else jnp.bitwise_not, self)
Tensor.__eq__ = _binop(jnp.equal)
Tensor.__ne__ = _binop(jnp.not_equal)
Tensor.__lt__ = _binop(jnp.less)
Tensor.__le__ = _binop(jnp.less_equal)
Tensor.__gt__ = _binop(jnp.greater)
Tensor.__ge__ = _binop(jnp.greater_equal)
Tensor.__and__ = _binop(jnp.bitwise_and)
Tensor.__or__ = _binop(jnp.bitwise_or)
Tensor.__xor__ = _binop(jnp.bitwise_xor)
Tensor.__lshift__ = _binop(jnp.left_shift)
Tensor.__rshift__ = _binop(jnp.right_shift)
Tensor.__hash__ = object.__hash__  # __eq__ override killed it; identity hash


# ---------------------------------------------------------------------------
# method attachment: x.foo(...) == paddle.foo(x, ...)
# ---------------------------------------------------------------------------

_METHOD_MODULES = [math, manipulation, linalg, logic, search, stat, creation, attribute]
_SKIP = {"to_tensor", "zeros", "ones", "full", "arange", "linspace", "eye",
         "empty", "meshgrid", "tril_indices", "triu_indices", "where",
         "einsum", "multi_dot", "broadcast_tensors", "scatter_nd",
         "hstack", "vstack", "dstack", "column_stack", "row_stack",
         "atleast_1d", "atleast_2d", "atleast_3d"}


def _attach():
    for mod in _METHOD_MODULES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or name.startswith("_"):
                continue
            fn = getattr(mod, name, None)
            if fn is None or not callable(fn):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # in-place variants
    import functools

    def make_inplace(fn):
        @functools.wraps(fn)
        def inplace(self, *a, **k):
            return self._rebind(fn(self, *a, **k))
        return inplace

    for name in ["add", "subtract", "multiply", "divide", "clip", "scale",
                 "floor", "ceil", "exp", "sqrt", "rsqrt", "reciprocal",
                 "round", "abs", "tanh", "sigmoid", "pow"]:
        fn = getattr(Tensor, name, None)
        if fn is not None and not hasattr(Tensor, name + "_"):
            setattr(Tensor, name + "_", make_inplace(fn))

    # x.where(cond-style): paddle Tensor.where(x, y) means where(self_cond?..)
    Tensor.where = lambda self, x, y, name=None: manipulation.where(self, x, y)
    Tensor.mean = math.mean
    Tensor.sum = math.sum
    Tensor.max = math.max
    Tensor.min = math.min
    Tensor.matmul = math.matmul
    Tensor.mm = math.matmul
    Tensor.norm = linalg.norm
    Tensor.transpose = manipulation.transpose
    Tensor.reshape = manipulation.reshape
    Tensor.unsqueeze = manipulation.unsqueeze
    Tensor.squeeze = manipulation.squeeze


_attach()
