"""Shape/layout manipulation ops. reference: python/paddle/tensor/manipulation.py.

On TPU these are free or cheap under XLA (layout assignment handles them);
`reshape`/`transpose` never copy in the compiled graph. The reference needs a
whole `stride/` kernel family (paddle/phi/kernels/stride/) for view semantics —
XLA's functional arrays make that machinery unnecessary.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dt
from ..framework.core import Tensor, execute

__all__ = [
    "reshape", "reshape_", "flatten", "transpose", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "concat", "stack", "split", "chunk", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "flip",
    "rot90", "roll", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add", "index_put",
    "take_along_axis", "put_along_axis", "masked_select", "masked_fill",
    "where", "slice", "strided_slice", "unbind", "unstack", "pad",
    "repeat_interleave", "moveaxis", "swapaxes", "as_complex", "as_real",
    "view", "view_as", "atleast_1d", "atleast_2d", "atleast_3d",
    "tensor_split", "hsplit", "vsplit", "dsplit", "hstack", "vstack",
    "dstack", "column_stack", "row_stack", "unflatten", "unfold",
    "flatten_", "cast", "crop", "tolist", "numel", "shard_index",
    "diagonal", "diagonal_scatter", "select_scatter", "slice_scatter",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return execute(lambda a: jnp.reshape(a, s), x, _name="reshape")


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def cast(x, dtype):
    return x.astype(dtype)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s0 = start_axis % nd if nd else 0
        s1 = stop_axis % nd if nd else 0
        new_shape = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return a.reshape(new_shape)
    return execute(f, x, _name="flatten")


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._rebind(flatten(x, start_axis, stop_axis))


def transpose(x, perm=None, name=None):
    p = None if perm is None else tuple(int(v) for v in perm)
    return execute(lambda a: jnp.transpose(a, p), x, _name="transpose")


def moveaxis(x, source, destination, name=None):
    return execute(lambda a: jnp.moveaxis(a, source, destination), x, _name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return execute(lambda a: jnp.swapaxes(a, axis0, axis1), x, _name="swapaxes")


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axes) if axes else a
    return execute(f, x, _name="squeeze")


def squeeze_(x, axis=None, name=None):
    return x._rebind(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    def f(a):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = [int(ax._data) if isinstance(ax, Tensor) else int(ax) for ax in axes]
        out = a
        for ax in sorted([ax % (out.ndim + 1 + 0) if ax < 0 else ax for ax in axes]):
            out = jnp.expand_dims(out, ax)
        return out
    return execute(f, x, _name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return x._rebind(unsqueeze(x, axis))


def concat(x, axis=0, name=None):
    ax = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    return execute(lambda *arrs: jnp.concatenate(arrs, ax), *x, _name="concat")


def stack(x, axis=0, name=None):
    return execute(lambda *arrs: jnp.stack(arrs, axis), *x, _name="stack")


def hstack(x, name=None):
    return execute(lambda *arrs: jnp.hstack(arrs), *x, _name="hstack")


def vstack(x, name=None):
    return execute(lambda *arrs: jnp.vstack(arrs), *x, _name="vstack")


def dstack(x, name=None):
    return execute(lambda *arrs: jnp.dstack(arrs), *x, _name="dstack")


def column_stack(x, name=None):
    return execute(lambda *arrs: jnp.column_stack(arrs), *x, _name="column_stack")


row_stack = vstack


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    def f(a):
        n = a.shape[ax]
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, ax))
        secs = [n - sum(s for s in num_or_sections if s not in (-1,)) if s == -1 else s
                for s in num_or_sections]
        idx = np.cumsum(secs)[:-1]
        return tuple(jnp.split(a, idx, ax))
    return list(execute(f, x, _name="split"))


def tensor_split(x, num_or_indices, axis=0, name=None):
    return list(execute(lambda a: tuple(jnp.array_split(a, num_or_indices, axis)), x, _name="tensor_split"))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    def f(a):
        return tuple(jnp.moveaxis(a, axis, 0))
    n = x.shape[axis]
    return list(execute(lambda a: tuple(jnp.take(a, i, axis) for i in range(n)), x, _name="unbind"))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times) if not isinstance(repeat_times, int) else (repeat_times,)
    return execute(lambda a: jnp.tile(a, reps), x, _name="tile")


def expand(x, shape, name=None):
    s = _shape_arg(shape)
    def f(a):
        target = list(s)
        # -1 means keep original dim
        off = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(target))
    return execute(f, x, _name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return execute(lambda a: jnp.broadcast_to(a, _shape_arg(shape)), x, _name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    return list(execute(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), *inputs, _name="broadcast_tensors"))


def flip(x, axis, name=None):
    return execute(lambda a: jnp.flip(a, axis), x, _name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return execute(lambda a: jnp.rot90(a, k, axes), x, _name="rot90")


def roll(x, shifts, axis=None, name=None):
    return execute(lambda a: jnp.roll(a, shifts, axis), x, _name="roll")


def gather(x, index, axis=0, name=None):
    def f(a, idx):
        return jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx, axis=axis)
    return execute(f, x, index, _name="gather")


def gather_nd(x, index, name=None):
    def f(a, idx):
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., i] for i in range(k))
        return a[flat_idx]
    return execute(f, x, index, _name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        idx = idx.reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        z = a.at[idx].set(jnp.zeros_like(upd))
        return z.at[idx].add(upd)
    return execute(f, x, index, updates, _name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        k = idx.shape[-1]
        ix = tuple(idx[..., i] for i in range(k))
        return a.at[ix].add(upd)
    return execute(f, x, index, updates, _name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    def f(idx, upd):
        a = jnp.zeros(_shape_arg(shape), upd.dtype)
        k = idx.shape[-1]
        ix = tuple(idx[..., i] for i in range(k))
        return a.at[ix].add(upd)
    return execute(f, index, updates, _name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    return execute(lambda a, i: jnp.take(a, i, axis=axis), x, index, _name="index_select")


def index_sample(x, index, name=None):
    return execute(lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index, _name="index_sample")


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return execute(f, x, index, value, _name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)
    return execute(f, x, value, *indices, _name="index_put")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return execute(lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices, _name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if not hasattr(v, "shape") or v.shape != i.shape else v
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        upd = jnp.zeros_like(a)
        if reduce == "add":
            dims = tuple(jnp.indices(i.shape))
            full_idx = list(dims)
            full_idx[axis] = i
            return a.at[tuple(full_idx)].add(v)
        if reduce in ("mul", "multiply"):
            dims = tuple(jnp.indices(i.shape))
            full_idx = list(dims)
            full_idx[axis] = i
            return a.at[tuple(full_idx)].multiply(v)
        raise ValueError(reduce)
    if not isinstance(values, Tensor):
        values = Tensor(jnp.broadcast_to(jnp.asarray(values, x_dtype(arr)), indices.shape))
    return execute(f, arr, indices, values, _name="put_along_axis")


def x_dtype(t):
    return t._data.dtype


def masked_select(x, mask, name=None):
    # dynamic output shape: the mask is concretized on host (documented
    # non-jittable), but the VALUE path stays a differentiable gather so
    # gradients scatter back into the selected positions
    m = np.broadcast_to(np.asarray(mask._data), x._data.shape)
    idx = jnp.asarray(np.nonzero(m.reshape(-1))[0], jnp.int32)
    return execute(lambda a: a.reshape(-1)[idx], x, _name="masked_select")


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value
    return execute(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask, _name="masked_fill")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return execute(lambda c, a, b: jnp.where(c, a, b), condition, x, y, _name="where")


def nonzero(x, as_tuple=False):
    a = np.asarray(x._data)
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


__all__.append("nonzero")


import builtins as _builtins

builtins_slice = _builtins.slice


def slice(input, axes, starts, ends, name=None):
    def f(a):
        sl = [builtins_slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(s._data) if isinstance(s, Tensor) else int(s)
            e = int(e._data) if isinstance(e, Tensor) else int(e)
            sl[ax] = builtins_slice(s, e)
        return a[tuple(sl)]
    return execute(f, input, _name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        sl = [builtins_slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins_slice(int(s), int(e), int(st))
        return a[tuple(sl)]
    return execute(f, x, _name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    def f(a):
        offs = offsets or [0] * a.ndim
        shp = list(shape)
        for i, s in enumerate(shp):
            if s == -1:
                shp[i] = a.shape[i] - offs[i]
        sl = tuple(builtins_slice(o, o + s) for o, s in zip(offs, shp))
        return a[sl]
    return execute(f, x, _name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    def f(a):
        p = [int(v._data) if isinstance(v, Tensor) else int(v) for v in pad] if not isinstance(pad, Tensor) else [int(v) for v in np.asarray(pad._data)]
        nd = a.ndim
        if len(p) == 2 * nd:
            if pad_from_left_axis:
                width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
            else:
                width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)][::-1]
        elif len(p) == 4 and nd == 4:
            # NCHW: pad H, W
            if data_format == "NCHW":
                width = [(0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])]
            else:
                width = [(0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)]
        elif len(p) == 2 and nd == 3:
            if data_format == "NCL":
                width = [(0, 0), (0, 0), (p[0], p[1])]
            else:
                width = [(0, 0), (p[0], p[1]), (0, 0)]
        elif len(p) == 6 and nd == 5:
            if data_format == "NCDHW":
                width = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
            else:
                width = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
        else:
            width = [(0, 0)] * (nd - len(p) // 2) + [(p[2 * i], p[2 * i + 1]) for i in range(len(p) // 2)]
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)
    return execute(f, x, _name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._data)
        total = int(reps.sum())
        return execute(
            lambda a, r: jnp.repeat(a, r, axis=axis, total_repeat_length=total),
            x, repeats, _name="repeat_interleave")
    return execute(lambda a: jnp.repeat(a, repeats, axis=axis), x, _name="repeat_interleave")


def as_complex(x, name=None):
    return execute(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, _name="as_complex")


def as_real(x, name=None):
    return execute(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x, _name="as_real")


def atleast_1d(*inputs, name=None):
    outs = [execute(jnp.atleast_1d, t, _name="atleast_1d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [execute(jnp.atleast_2d, t, _name="atleast_2d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [execute(jnp.atleast_3d, t, _name="atleast_3d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def unflatten(x, axis, shape, name=None):
    def f(a):
        ax = axis % a.ndim
        return a.reshape(a.shape[:ax] + tuple(shape) + a.shape[ax + 1:])
    return execute(f, x, _name="unflatten")


def unfold(x, axis, size, step, name=None):
    return execute(lambda a: _unfold_ref(a, axis, size, step), x, _name="unfold")


def _unfold_ref(a, axis, size, step):
    n = (a.shape[axis] - size) // step + 1
    slices = [jax.lax.dynamic_slice_in_dim(a, i * step, size, axis) for i in range(n)]
    stacked = jnp.stack(slices, axis=axis)  # (..., n, size_at_axis+1, ...)
    return jnp.moveaxis(stacked, axis + 1, -1)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return execute(lambda a: jnp.diagonal(a, offset, axis1, axis2), x, _name="diagonal")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, b):
        n1, n2 = a.shape[axis1], a.shape[axis2]
        diag_len = min(n1, n2 - offset) if offset >= 0 else min(n1 + offset, n2)
        rows = np.arange(diag_len) + (-offset if offset < 0 else 0)
        cols = np.arange(diag_len) + (offset if offset > 0 else 0)
        sl = [builtins_slice(None)] * a.ndim
        out = a
        for k in range(diag_len):
            sel = list(sl)
            sel[axis1] = int(rows[k])
            sel[axis2] = int(cols[k])
            out = out.at[tuple(sel)].set(jnp.take(b, k, axis=-1))
        return out
    return execute(f, x, y, _name="diagonal_scatter")


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        sl = [builtins_slice(None)] * a.ndim
        sl[axis] = index
        return a.at[tuple(sl)].set(v)
    return execute(f, x, values, _name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        sl = [builtins_slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins_slice(int(s), int(e), int(st))
        return a.at[tuple(sl)].set(v)
    return execute(f, x, value, _name="slice_scatter")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(a):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        hi = lo + shard_size
        in_shard = (a >= lo) & (a < hi)
        return jnp.where(in_shard, a - lo, ignore_value)
    return execute(f, input, _name="shard_index")


def tolist(x):
    return np.asarray(x._data).tolist()


def numel(x, name=None):
    return Tensor(jnp.asarray(x._data.size, dtype=jnp.int64))
