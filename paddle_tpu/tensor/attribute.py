"""Tensor attribute queries. reference: python/paddle/tensor/attribute.py."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework import dtypes as _dt
from ..framework.core import Tensor

__all__ = ["rank", "shape", "is_complex", "is_floating_point", "is_integer",
           "real", "imag", "is_tensor"]

from .math import real, imag  # noqa: F401
from .logic import is_tensor  # noqa: F401


def rank(input):
    return Tensor(jnp.asarray(input.ndim, dtype=jnp.int32))


def shape(input):
    return Tensor(jnp.asarray(input.shape, dtype=jnp.int32))


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)
