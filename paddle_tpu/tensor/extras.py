"""Long-tail tensor ops closing the reference's top-level API surface.

reference: python/paddle/tensor/math.py, manipulation.py, linalg.py —
the less-common public ops (special functions, distance matrices,
structured creation) that reference code still imports from `paddle.*`.
All are jax compositions dispatched through execute() so the eager tape
and FD grad gate cover them.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute

__all__ = [
    "add_n", "block_diag", "broadcast_shape", "cartesian_prod", "cdist",
    "combinations", "diag_embed", "frexp", "gammainc", "gammaincc",
    "gammaln", "histogram_bin_edges", "index_fill", "isin", "logcumsumexp",
    "masked_scatter", "multigammaln", "pdist", "polygamma", "reduce_as",
    "renorm", "reverse", "sgn", "signbit", "sinc", "take", "trace",
    "vander", "as_strided",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list. reference: math.py add_n."""
    if isinstance(inputs, Tensor):
        return execute(lambda a: a, inputs, _name="add_n")
    def f(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return execute(f, *inputs, _name="add_n")


def block_diag(inputs, name=None):
    def f(*arrs):
        arrs = [a if a.ndim == 2 else a.reshape(1, -1) for a in arrs]
        return jax.scipy.linalg.block_diag(*arrs)
    return execute(f, *inputs, _name="block_diag")


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def cartesian_prod(x, name=None):
    def f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return execute(f, *x, _name="cartesian_prod")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise distances between row vectors. reference: linalg.py cdist."""
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 1e-30))
        if p == float("inf"):
            return jnp.max(jnp.abs(d), -1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype), -1)
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
    return execute(f, x, y, _name="cdist")


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances (upper triangle, row-major)."""
    def f(a):
        n = a.shape[0]
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            m = jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 1e-30))
        elif p == float("inf"):
            m = jnp.max(jnp.abs(d), -1)
        else:
            m = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return m[iu]
    return execute(f, x, _name="pdist")


def combinations(x, r=2, with_replacement=False, name=None):
    n = int(x.shape[0])
    import itertools as it
    idx = list(it.combinations_with_replacement(range(n), r)
               if with_replacement else it.combinations(range(n), r))
    idx_arr = jnp.asarray(np.asarray(idx, np.int32).reshape(-1, r)
                          if idx else np.zeros((0, r), np.int32))
    return execute(lambda a: a[idx_arr], x, _name="combinations")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched vectors -> batched diagonal matrices.
    reference: tensor/creation.py diag_embed."""
    def f(a):
        m = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
        i = jnp.arange(a.shape[-1])
        rows = i + max(-offset, 0)
        cols = i + max(offset, 0)
        out = base.at[..., rows, cols].set(a)
        nd = out.ndim
        return jnp.moveaxis(out, (nd - 2, nd - 1), (dim1 % nd, dim2 % nd))
    return execute(f, input, _name="diag_embed")


def frexp(x, name=None):
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(jnp.int32)
    return execute(f, x, _name="frexp")


def gammaln(x, name=None):
    return execute(jax.scipy.special.gammaln, x, _name="gammaln")


def gammainc(x, y, name=None):
    return execute(jax.scipy.special.gammainc, x, y, _name="gammainc")


def gammaincc(x, y, name=None):
    return execute(jax.scipy.special.gammaincc, x, y, _name="gammaincc")


def multigammaln(x, p, name=None):
    return execute(lambda a: jax.scipy.special.multigammaln(a, p), x,
                   _name="multigammaln")


def polygamma(x, n, name=None):
    return execute(lambda a: jax.scipy.special.polygamma(n, a), x,
                   _name="polygamma")


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    def f(a):
        rng = None if (min == 0 and max == 0) else (min, max)
        return jnp.histogram_bin_edges(a, bins=bins, range=rng)
    return execute(f, input, _name="histogram_bin_edges")


def index_fill(x, index, axis, value, name=None):
    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return execute(f, x, index, _name="index_fill")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return execute(lambda a, t: jnp.isin(a, t, invert=invert), x, test_x,
                   _name="isin")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Numerically-stable cumulative logsumexp. reference: math.py."""
    def f(a):
        if axis is None:
            arr = a.reshape(-1)
            ax = 0
        else:
            arr, ax = a, axis
        out = jax.lax.cumlogsumexp(arr.astype(jnp.float32), axis=ax)
        return out.astype(dtype or a.dtype) if jnp.issubdtype(
            a.dtype, jnp.floating) else out
    return execute(f, x, _name="logcumsumexp")


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions of x with consecutive elements of value."""
    def f(a, m, v):
        flat_m = m.reshape(-1) if m.shape == a.shape else \
            jnp.broadcast_to(m, a.shape).reshape(-1)
        pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        src = v.reshape(-1)[jnp.clip(pos, 0, v.size - 1)]
        return jnp.where(flat_m, src, a.reshape(-1)).reshape(a.shape)
    return execute(f, x, mask, value, _name="masked_scatter")


def reduce_as(x, target, name=None):
    """Sum-reduce x to the shape of target (grad-of-broadcast semantics)."""
    def f(a, t):
        extra = a.ndim - t.ndim
        if extra:
            a = jnp.sum(a, axis=tuple(range(extra)))
        axes = tuple(i for i in range(a.ndim) if t.shape[i] == 1
                     and a.shape[i] != 1)
        if axes:
            a = jnp.sum(a, axis=axes, keepdims=True)
        return a
    return execute(f, x, target, _name="reduce_as")


def renorm(x, p, axis, max_norm, name=None):
    """Clip each slice along axis to p-norm <= max_norm."""
    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        if p == float("inf"):
            norms = jnp.max(jnp.abs(flat), axis=1)
        else:
            norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return execute(f, x, _name="renorm")


def reverse(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return execute(lambda a: jnp.flip(a, ax), x, _name="reverse")


def sgn(x, name=None):
    """Complex-aware sign: x/|x| (0 where x == 0)."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-30))
        return jnp.sign(a)
    return execute(f, x, _name="sgn")


def signbit(x, name=None):
    return execute(jnp.signbit, x, _name="signbit")


def sinc(x, name=None):
    return execute(jnp.sinc, x, _name="sinc")


def take(x, index, mode="raise", name=None):
    """Flat-index gather. reference: math.py take (mode raise/wrap/clip)."""
    def f(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx2 = jnp.mod(idx, n)
        else:  # clip (and 'raise': XLA clamps; OOB cannot trap on TPU)
            idx2 = jnp.clip(idx, -n, n - 1)
        idx2 = jnp.where(idx2 < 0, idx2 + n, idx2)
        return flat[idx2]
    return execute(f, x, index, _name="take")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return execute(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                       axis2=axis2), x, _name="trace")


def vander(x, n=None, increasing=False, name=None):
    return execute(lambda a: jnp.vander(a, N=n, increasing=increasing), x,
                   _name="vander")


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view as an explicit gather (XLA has no aliasing views;
    reference: paddle/phi/kernels/stride/). Indices are computed from the
    requested strides over the flattened input."""
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)

    def f(a):
        flat = a.reshape(-1)
        idx = jnp.asarray(offset)
        for dim, (sz, st) in enumerate(zip(shape, stride)):
            ix = jnp.arange(sz) * st
            expand = [None] * len(shape)
            expand[dim] = slice(None)
            idx = idx + ix[tuple(expand)]
        return flat[idx]
    return execute(f, x, _name="as_strided")
