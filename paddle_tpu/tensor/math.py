"""Elementwise + reduction math ops. reference: python/paddle/tensor/math.py.

Every op is a pure jax function routed through framework.core.execute, which
records a vjp node when grads are needed. XLA fuses chains of these
elementwise ops into single TPU kernels (replacing the reference's CINN
fusion pass, paddle/cinn/hlir/...), so op granularity here costs nothing
under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dt
from ..framework.core import Tensor, execute

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _unary(name, f):
    def op(x, name=None):
        return execute(f, x, _name=name)
    op.__name__ = name
    __all__.append(name)
    return op


def _promote_binary(f):
    """Apply paddle-ish binary promotion: int tensor + float scalar -> float."""
    def g(a, b):
        if isinstance(a, jax.Array) or isinstance(b, jax.Array):
            pass
        return f(a, b)
    return g


def _binary(name, f):
    def op(x, y, name=None):
        return execute(f, x, y, _name=name)
    op.__name__ = name
    __all__.append(name)
    return op


# ---- unary ----------------------------------------------------------------
abs = _unary("abs", jnp.abs)
acos = _unary("acos", jnp.arccos)
acosh = _unary("acosh", jnp.arccosh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
ceil = _unary("ceil", jnp.ceil)
cos = _unary("cos", jnp.cos)
cosh = _unary("cosh", jnp.cosh)
digamma = _unary("digamma", jax.scipy.special.digamma)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
floor = _unary("floor", jnp.floor)
frac = _unary("frac", lambda a: a - jnp.trunc(a))
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
log = _unary("log", jnp.log)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
log2 = _unary("log2", jnp.log2)
neg = _unary("neg", jnp.negative)
reciprocal = _unary("reciprocal", jnp.reciprocal)
round = _unary("round", jnp.round)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
sign = _unary("sign", jnp.sign)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
trunc = _unary("trunc", jnp.trunc)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponential_ = None  # random module


@_export
def logit(x, eps=None, name=None):
    def f(a):
        a2 = jnp.clip(a, eps, 1 - eps) if eps else a
        return jnp.log(a2 / (1 - a2))
    return execute(f, x, _name="logit")


# ---- binary ---------------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = _binary("remainder", jnp.remainder)
floor_mod = _binary("floor_mod", jnp.mod)
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
logaddexp = _binary("logaddexp", jnp.logaddexp)
hypot = _binary("hypot", jnp.hypot)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
ldexp = _binary("ldexp", lambda a, b: a * (2.0 ** b.astype(jnp.float32) if hasattr(b, "astype") else 2.0 ** b))
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
heaviside = _binary("heaviside", jnp.heaviside)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", lambda a, b: jnp.outer(a, b))
kron = _binary("kron", jnp.kron)


@_export
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out.astype(a.dtype) if jnp.issubdtype(a.dtype, jnp.inexact) else out
    return execute(f, x, scale, bias, _name="scale")


@_export
def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return execute(lambda a: jnp.clip(a, lo, hi), x, _name="clip")


@_export
def lerp(x, y, weight, name=None):
    return execute(lambda a, b, w: a + w * (b - a), x, y, weight, _name="lerp")


@_export
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return execute(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, _name="addmm")


@_export
def multiplex(inputs, index, name=None):
    def f(idx, *arrs):
        stacked = jnp.stack(arrs, 0)
        return jnp.take_along_axis(
            stacked, idx.reshape((1, -1) + (1,) * (stacked.ndim - 2)), axis=0
        )[0]
    return execute(lambda *args: f(args[-1], *args[:-1]), *inputs, index, _name="multiplex")


@_export
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return execute(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x, _name="nan_to_num")


@_export
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return execute(lambda a: scale_b * jnp.tanh(scale_a * a), x, _name="stanh")


# ---- reductions -----------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        ax = np.asarray(axis._data)
        return tuple(int(v) for v in np.atleast_1d(ax))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduction(name, f, bool_to_int64=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _axis(axis)
        def g(a):
            if bool_to_int64 and (a.dtype == jnp.bool_):
                a = a.astype(jnp.int64)
            kw = {}
            if dtype is not None:
                kw["dtype"] = _dt.convert_dtype(dtype)
            return f(a, axis=ax, keepdims=keepdim, **kw)
        return execute(g, x, _name=name)
    op.__name__ = name
    __all__.append(name)
    return op


sum = _reduction("sum", jnp.sum, bool_to_int64=True)
mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod)
max = _reduction("max", jnp.max)
min = _reduction("min", jnp.min)
amax = _reduction("amax", jnp.max)
amin = _reduction("amin", jnp.min)
nansum = _reduction("nansum", jnp.nansum)
nanmean = _reduction("nanmean", jnp.nanmean)
all = _reduction("all", jnp.all)
any = _reduction("any", jnp.any)
logsumexp = _reduction("logsumexp", jax.scipy.special.logsumexp)


@_export
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return execute(lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64), x, _name="count_nonzero")


@_export
def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jnp.cumsum(a, ax, dtype=_dt.convert_dtype(dtype))
    return execute(f, x, _name="cumsum")


@_export
def cumprod(x, dim=None, dtype=None, name=None):
    return execute(lambda a: jnp.cumprod(a, dim, dtype=_dt.convert_dtype(dtype)), x, _name="cumprod")


@_export
def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = 0 if axis is None else axis
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        idx = jnp.argmax((arr[..., None] if False else arr) == vals, axis=ax)
        # recompute indices via scan over argmax trick
        n = arr.shape[ax]
        ar = jnp.arange(n)
        shape = [1] * arr.ndim
        shape[ax] = n
        ar = ar.reshape(shape)
        eq = arr == vals
        idxs = jnp.where(eq, ar, -1)
        idx = jax.lax.associative_scan(jnp.maximum, idxs, axis=ax)
        return vals, idx.astype(_dt.convert_dtype(dtype))
    return execute(f, x, _name="cummax")


@_export
def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = 0 if axis is None else axis
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        n = arr.shape[ax]
        ar = jnp.arange(n)
        shape = [1] * arr.ndim
        shape[ax] = n
        ar = ar.reshape(shape)
        idxs = jnp.where(arr == vals, ar, -1)
        idx = jax.lax.associative_scan(jnp.maximum, idxs, axis=ax)
        return vals, idx.astype(_dt.convert_dtype(dtype))
    return execute(f, x, _name="cummin")


@_export
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(ya, xa=None):
        d = dx if dx is not None else 1.0
        if xa is not None:
            d = jnp.diff(xa, axis=axis)
        else:
            d = jnp.asarray(d)
        sl1 = [slice(None)] * ya.ndim
        sl2 = [slice(None)] * ya.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        avg = (ya[tuple(sl1)] + ya[tuple(sl2)]) / 2.0
        return jnp.cumsum(avg * d, axis=axis)
    if x is None:
        return execute(f, y, _name="cumulative_trapezoid")
    return execute(f, y, x, _name="cumulative_trapezoid")


@_export
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(ya, xa=None):
        if xa is not None:
            return jnp.trapezoid(ya, xa, axis=axis)
        return jnp.trapezoid(ya, dx=dx if dx is not None else 1.0, axis=axis)
    if x is None:
        return execute(f, y, _name="trapezoid")
    return execute(f, y, x, _name="trapezoid")


@_export
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    kw = {}
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)
    def f(a, *rest):
        i = 0
        pre = app = None
        if prepend is not None:
            pre = rest[i]; i += 1
        if append is not None:
            app = rest[i]
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return execute(f, *args, _name="diff")


# ---- matmul & friends live in linalg, dot products here for parity --------
@_export
def dot(x, y, name=None):
    def f(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return execute(f, x, y, _name="dot")


@_export
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """reference: python/paddle/tensor/linalg.py:191; kernel
    paddle/phi/kernels/gpu/matmul_kernel.cu → here a single jnp.matmul the
    XLA compiler tiles onto the MXU."""
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return execute(f, x, y, _name="matmul")


mm = matmul
__all__.append("mm")


@_export
def bmm(x, y, name=None):
    return execute(jnp.matmul, x, y, _name="bmm")


@_export
def isfinite(x, name=None):
    return execute(jnp.isfinite, x, _name="isfinite")


@_export
def isinf(x, name=None):
    return execute(jnp.isinf, x, _name="isinf")


@_export
def isnan(x, name=None):
    return execute(jnp.isnan, x, _name="isnan")


@_export
def isneginf(x, name=None):
    return execute(jnp.isneginf, x, _name="isneginf")


@_export
def isposinf(x, name=None):
    return execute(jnp.isposinf, x, _name="isposinf")


@_export
def isreal(x, name=None):
    return execute(jnp.isreal, x, _name="isreal")


@_export
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return execute(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y, _name="isclose")


@_export
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return execute(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y, _name="allclose")


@_export
def equal_all(x, y, name=None):
    return execute(lambda a, b: jnp.array_equal(a, b), x, y, _name="equal_all")


@_export
def increment(x, value=1.0, name=None):
    out = execute(lambda a: a + value, x, _name="increment")
    x._rebind(out)
    return x


@_export
def accuracy(input, label, k=1, correct=None, total=None, name=None):
    def f(inp, lab):
        topk_idx = jax.lax.top_k(inp, k)[1]
        lab2 = lab.reshape(-1, 1)
        hit = jnp.any(topk_idx == lab2, axis=1)
        return jnp.mean(hit.astype(jnp.float32))
    return execute(f, input, label, _name="accuracy")
