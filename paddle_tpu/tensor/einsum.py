"""einsum. reference: python/paddle/tensor/einsum.py — here one call into
jnp.einsum, which XLA maps straight onto MXU dot_generals."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import execute

__all__ = ["einsum"]


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return execute(lambda *arrs: jnp.einsum(equation, *arrs), *operands, _name="einsum")
