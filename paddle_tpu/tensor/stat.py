"""Statistics ops. reference: python/paddle/tensor/stat.py."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import execute, Tensor

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "numel"]

from .math import mean  # noqa: F401
from .manipulation import numel  # noqa: F401


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return execute(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x, _name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return execute(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x, _name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=_axis(axis), keepdims=keepdim)
        # mode='min': lower median + index
        ax = _axis(axis)
        if ax is None:
            flat = a.reshape(-1)
            n = flat.shape[0]
            s = jnp.sort(flat)
            si = jnp.argsort(flat, stable=True)
            k = (n - 1) // 2
            return s[k], si[k].astype(jnp.int64)
        n = a.shape[ax]
        k = (n - 1) // 2
        s = jnp.sort(a, axis=ax)
        si = jnp.argsort(a, axis=ax, stable=True)
        v = jnp.take(s, k, axis=ax)
        i = jnp.take(si, k, axis=ax).astype(jnp.int64)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i
    return execute(f, x, _name="median")


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return execute(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x, _name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    def f(a):
        return jnp.quantile(a.astype(jnp.float64) if False else a, qv.astype(a.dtype) if hasattr(qv, "astype") else qv,
                            axis=_axis(axis), keepdims=keepdim, method=interpolation)
    return execute(f, x, _name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    def f(a):
        return jnp.nanquantile(a, qv.astype(a.dtype) if hasattr(qv, "astype") else qv,
                               axis=_axis(axis), keepdims=keepdim, method=interpolation)
    return execute(f, x, _name="nanquantile")
