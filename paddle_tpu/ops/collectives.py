"""Collective-bearing primitive tags for the PIR scheduler.

The collective-overlap pass (pir/overlap.py) and the CostModel's
exposed-communication term (pir/analysis.py) need to know which ops
move bytes over the interconnect rather than HBM. In captured programs
jax collectives show up either as top-level eqns (``psum`` inside a
pmap'd body) or nested inside a ``shard_map``/``pjit`` eqn's jaxpr —
``collective_traffic`` walks both.

Traffic factors approximate ring-algorithm bytes-on-wire per element of
the op's payload: an all-reduce moves ~2x the buffer (reduce-scatter
phase + all-gather phase), one-phase collectives ~1x, ppermute exactly
one hop. The factor multiplies the LARGER of the eqn's input/output
footprint, so gather-like ops are priced on their wide side.
"""

from __future__ import annotations

__all__ = ["COLLECTIVE_PRIMITIVES", "collective_traffic",
           "is_collective_eqn"]

# closed registry: primitive name -> ring traffic factor (bytes moved on
# the interconnect per payload byte). Names cover every collective the
# distributed layer emits (paddle_tpu/distributed/collective.py wraps
# exactly these lax primitives) plus the shard_map-era aliases.
COLLECTIVE_PRIMITIVES = {
    "psum": 2.0,            # all-reduce: reduce-scatter + all-gather
    "psum2": 2.0,           # shard_map's all-reduce primitive
    "pmax": 2.0,
    "pmin": 2.0,
    "all_gather": 1.0,
    "all_gather_invariant": 1.0,
    "reduce_scatter": 1.0,
    "psum_scatter": 1.0,
    "all_to_all": 1.0,
    "ppermute": 1.0,        # one hop
}

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "float8_e4m3fn": 1,
    "float8_e5m2": 1,
}


def _aval_bytes(aval):
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * _DTYPE_BYTES.get(str(getattr(aval, "dtype", "float32")), 4)


def _inner_jaxprs(params):
    found = []
    for v in params.values():
        inner = getattr(v, "jaxpr", None)          # ClosedJaxpr
        if inner is not None and hasattr(inner, "eqns"):
            found.append(inner)
        elif hasattr(v, "eqns"):                   # bare Jaxpr
            found.append(v)
    return found


def is_collective_eqn(eqn) -> bool:
    return eqn.primitive.name in COLLECTIVE_PRIMITIVES


def collective_traffic(eqn, depth: int = 0) -> list:
    """[(primitive name, wire bytes)] for every collective reachable
    from this eqn — the eqn itself, or collectives nested in its
    sub-jaxprs (shard_map / pjit / scan bodies; scan trips multiply)."""
    if depth > 8:           # pathological nesting: stop walking, stay finite
        return []
    name = eqn.primitive.name
    if name in COLLECTIVE_PRIMITIVES:
        payload = max(
            sum(_aval_bytes(iv.aval) for iv in eqn.invars
                if hasattr(iv, "aval")),
            sum(_aval_bytes(ov.aval) for ov in eqn.outvars))
        return [(name, float(payload) * COLLECTIVE_PRIMITIVES[name])]
    found = []
    inner = _inner_jaxprs(eqn.params)
    if inner:
        trips = float(eqn.params.get("length", 1) or 1)
        for j in inner:
            body = j.jaxpr if hasattr(j, "jaxpr") else j
            for sub in getattr(body, "eqns", ()):
                for cname, nbytes in collective_traffic(sub, depth + 1):
                    found.append((cname, nbytes * trips))
    return found
