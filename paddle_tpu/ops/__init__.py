"""paddle_tpu.ops — TPU kernel layer (Pallas + shard_map collectives).

The analog of paddle/phi/kernels/fusion + incubate fused ops, but as a
small set of hand-scheduled Pallas kernels for exactly the ops XLA fuses
poorly: flash attention, ring attention (context parallelism).
"""

from . import pallas  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
