"""Runtime kernel autotuning: measure candidate Pallas configs, cache winners.

reference capability: paddle/phi/kernels/autotune/ — AutoTuneBase
(auto_tune_base.h) times candidate kernels on first use, KernelCallback
cache (cache.h) memoizes the winner per input signature, and
switch_autotune.cc exposes the global toggle; layout autotuning hooks in
eager (fluid/eager/eager_layout_auto_tune.h). The python knob is
paddle.incubate.autotune.set_config.

TPU-native design: the tunables are Pallas grid/block shapes (block_q,
block_k for flash attention — the VMEM-tiling equivalent of the
reference's algorithm choice). Candidates are compiled and timed ONCE per
(kernel, shape-signature, device) on synthetic inputs, so tuning can run
even while the caller is being jit-traced; the winner is cached
process-wide. Off by default (FLAGS_use_autotune, like the reference's
switch) because timing compiles every candidate.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import jax

from ...framework import flags as _flags

__all__ = ["AlgorithmCache", "autotune", "enable_autotune",
           "disable_autotune", "autotune_enabled", "autotune_status"]

_flags.define_flag(
    "use_autotune", False,
    "time candidate Pallas block configs on first use and cache the winner "
    "(reference: FLAGS_use_autotune, phi/kernels/autotune/switch_autotune.cc)")


# per-key candidate->ms spreads from the most recent tuning runs
timing_log: dict = {}


class AlgorithmCache:
    """Winner cache + hit/miss stats (reference: autotune/cache.h)."""

    def __init__(self):
        self._cache: dict[Any, Any] = {}
        self.hits = 0
        self.misses = 0

    def get(self, key):
        if key in self._cache:
            self.hits += 1
            return self._cache[key]
        self.misses += 1
        return None

    def put(self, key, value):
        self._cache[key] = value

    def clear(self):
        self._cache.clear()
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return len(self._cache)


_global_cache = AlgorithmCache()


def enable_autotune():
    _flags.set_flags({"use_autotune": True})


def disable_autotune():
    _flags.set_flags({"use_autotune": False})


def autotune_enabled() -> bool:
    return bool(_flags.flag_value("use_autotune"))


def autotune_status():
    """reference: switch_autotune.cc AutoTuneStatus."""
    return {"enabled": autotune_enabled(), "size": len(_global_cache),
            "cache_hits": _global_cache.hits,
            "cache_misses": _global_cache.misses}


def _time_once(fn: Callable[[], Any], repeats: int = 2) -> float:
    """Best-of-N wall time of fn() (fn must block until ready)."""
    fn()  # compile + warm up
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def autotune(key, candidates: Sequence[Any], make_runner, default=None,
             repeats: int = 2):
    """Pick the fastest candidate for `key`, caching the winner.

    make_runner(candidate) -> zero-arg callable that executes the kernel
    with that config on synthetic inputs and blocks until ready, or raises
    to disqualify the candidate (e.g. VMEM overflow). Falls back to
    `default` (or the first candidate) if tuning is disabled or every
    candidate fails.
    """
    if default is None:
        default = candidates[0]
    if not autotune_enabled():
        return default
    cached = _global_cache.get(key)
    if cached is not None:
        return cached
    best, best_t = default, float("inf")
    timings = {}
    for cand in candidates:
        try:
            t = _time_once(make_runner(cand), repeats)
        except Exception:
            continue  # config not compilable on this device/shape
        timings[str(cand)] = round(t * 1e3, 3)
        if t < best_t:
            best, best_t = cand, t
    _global_cache.put(key, best)
    # full spread kept separately (not in the winner cache — it would
    # skew hit/size stats), for offline analysis when baking shipped
    # defaults: close seconds-place timings mean a noise-sensitive winner
    timing_log[key] = timings
    return best


def clear_cache():
    _global_cache.clear()
