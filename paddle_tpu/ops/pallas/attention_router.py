"""Per-shape attention backend router.

reference capability: paddle/phi/kernels/autotune/ (per-signature algorithm
choice) + python/paddle/nn/functional/flash_attention.py's
sdp_kernel-style backend selection — generalized into the shape-keyed
dispatch the r5 hardware A/B demanded: the Pallas flash kernel LOSES to
dense XLA at most production shapes (fwd 0.71-0.86x dense at s1024/s2048)
and wins at others (1.23x at s4096), so a single fixed backend is wrong
in both directions.

Design (three sources, in priority order, every decision carrying
provenance):

1. **Baked ledger** — a versioned on-disk table
   (``attention_ledger.json`` next to this module, or
   ``FLAGS_attention_ledger_path``) written by
   ``tools/bake_flash_blocks.py --ledger`` from real hardware timings
   (``.flash_vs_xla.json``) and end-to-end train A/Bs
   (``.bench_tpu_wins.jsonl``).  End-to-end entries (exact
   batch*heads match) outrank isolated-kernel entries: r5 measured the
   full-pallas backward WINNING end-to-end (0.4261 vs 0.4063 MFU) at the
   535m shape even though isolated timing favored the hybrid — HBM
   pressure from the O(S^2) remat buffer dominates the kernel gap.
   Ledger entries are ignored on a different device_kind.
2. **Measurement fallback** — on a ledger miss with a reachable TPU,
   time flash-vs-dense directly (scan-amortized, like the block
   autotuner); on CPU, a deterministic analytic roofline proxy (clearly
   labeled: a hypothesis, not a measurement).
3. **Heuristic** — the legacy seq/head_dim thresholds, only when
   measurement is disabled or fails.

The router covers fwd and bwd independently: fwd=pallas + bwd=xla is the
hybrid (flash forward, dense-remat backward) that wins at zero-padded
head dims (d96).  ``nn/functional`` attention, the flash custom-vjp
backward, ``incubate`` fused ops, ``inference/serving`` prefill, and
``bench.py`` all consult this module, so a backend choice is made once,
per shape, from data — and a re-bake after a hardware session updates
every call site at once.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

from ...framework import flags as _flags

__all__ = ["Decision", "route", "load_ledger", "ledger_blocks",
           "packed_grid_enabled", "decision_log", "clear_routing_cache",
           "LEDGER_FORMAT"]

LEDGER_FORMAT = 1

_DEFAULT_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "attention_ledger.json")

_flags.define_flag(
    "attention_router", "auto",
    "per-shape attention backend selection: 'auto' (baked ledger, then "
    "measurement fallback, then heuristic), 'ledger' (ledger or heuristic "
    "only — never measure), 'heuristic' (legacy thresholds; ignores the "
    "ledger)")
_flags.define_flag(
    "attention_ledger_path", "",
    "override path for the baked attention-backend ledger ('' = the "
    "attention_ledger.json shipped next to ops/pallas/attention_router.py)")


@dataclasses.dataclass(frozen=True)
class Decision:
    """One routed choice for an attention shape.

    fwd/bwd: 'pallas' or 'xla'.  fwd=pallas + bwd=xla is the hybrid
    (flash forward, dense-remat backward).  blocks_* are (block_q,
    block_k) VMEM tilings when the ledger recorded them (None = use the
    kernel default).  packed_grid: whether the triangle-packed causal
    grid is enabled for this decision's device.  source is machine-
    readable ('ledger-e2e' | 'ledger' | 'measured-tpu' | 'proxy' |
    'heuristic'); provenance is the human-readable audit string."""

    fwd: str
    bwd: str
    blocks_fwd: Optional[tuple] = None
    blocks_bwd: Optional[tuple] = None
    packed_grid: bool = False
    source: str = "heuristic"
    provenance: str = ""


# --------------------------------------------------------------------------
# ledger loading
# --------------------------------------------------------------------------

_ledger_cache: dict[str, Any] = {}
_route_cache: dict[Any, Decision] = {}
_decision_log: list[tuple] = []


def _ledger_path() -> str:
    return _flags.flag_value("attention_ledger_path") or _DEFAULT_LEDGER


def load_ledger(path: Optional[str] = None):
    """Parse (and cache) the baked ledger; None when absent or when the
    on-disk format version is not the one this code understands (a stale
    table must fail OPEN to the measurement/heuristic path, never
    silently misroute)."""
    path = path or _ledger_path()
    if path in _ledger_cache:
        return _ledger_cache[path]
    doc = None
    try:
        with open(path) as f:
            parsed = json.load(f)
        if isinstance(parsed, dict) and \
                parsed.get("ledger_format") == LEDGER_FORMAT:
            doc = parsed
    except Exception:
        doc = None
    _ledger_cache[path] = doc
    return doc


def clear_routing_cache():
    """Drop cached ledgers and decisions (tests; after re-baking)."""
    _ledger_cache.clear()
    _route_cache.clear()
    _decision_log.clear()


def decision_log():
    """[(key, Decision)] for every distinct shape routed this process —
    bench.py and the serving engine surface these for audit."""
    return list(_decision_log)


def _norm_dtype(dtype) -> str:
    s = str(dtype)
    return s.split(".")[-1].replace("'>", "").replace("<class ", "")


def _device_kind(platform: Optional[str]) -> str:
    if platform is None or platform == "tpu":
        try:
            import jax
            if jax.default_backend() == "tpu":
                return getattr(jax.devices()[0], "device_kind", "tpu")
        except Exception:
            pass
    return platform or "cpu"


def _match_entries(ledger, bh, sq, sk, d, dtype, causal, device_kind):
    """-> (e2e_entry, isolated_entry) matching this shape (either None).

    End-to-end entries need an exact (seq, head_dim, bh) match — they
    describe one measured train config.  Isolated entries match on
    (seq, head_dim, causal, dtype) with the nearest recorded batch*heads
    (block ranking depends on grid parallelism, so a bh=8 winner is a
    weaker prior for a bh=128 caller — prefer the closest)."""
    if ledger is None or sq != sk:
        return None, None
    if ledger.get("device_kind") and ledger["device_kind"] != device_kind:
        return None, None

    def _ok(e):
        return (e.get("seq") == sq and e.get("head_dim") == d
                and bool(e.get("causal", True)) == bool(causal)
                and e.get("dtype", "bfloat16") == dtype)

    e2e = None
    for e in ledger.get("end_to_end", []):
        if _ok(e) and e.get("bh") == bh:
            e2e = e
            break
    isolated = None
    best_gap = None
    for e in ledger.get("entries", []):
        if not _ok(e):
            continue
        gap = abs((e.get("bh") or 0) - bh)
        if best_gap is None or gap < best_gap:
            isolated, best_gap = e, gap
    return e2e, isolated


def ledger_blocks(kind: str, bh: int, sq: int, sk: int, d: int, dtype,
                  causal: bool, device_kind: Optional[str] = None):
    """(block_q, block_k) the ledger recorded for this shape, or None.
    Consulted by the flash kernels' block resolution when runtime
    autotune is off — the versioned successor of _SHIPPED_BLOCKS."""
    dk = device_kind or _device_kind(None)
    _, iso = _match_entries(load_ledger(), bh, sq, sk, d,
                            _norm_dtype(dtype), causal, dk)
    if iso is None:
        return None
    blocks = iso.get("blocks_fwd" if kind == "fwd" else "blocks_bwd")
    if blocks and blocks[0] <= sq and blocks[1] <= sk:
        return tuple(blocks)
    return None


def epilogue_fusion_wins(bh: int, sq: int, sk: int, d: int, dtype,
                         causal: bool = True,
                         device_kind: Optional[str] = None) -> bool:
    """Whether the baked ledger marks the fused RMSNorm+residual flash
    epilogue a winner at this shape (entry field `fused_epilogue_wins`,
    written by the bake tool once a hardware A/B measures it). False on
    any miss: the wider fusion is opt-in per measured shape — exactly
    the FlashFuser argument, applied with evidence."""
    dk = device_kind or _device_kind(None)
    _, iso = _match_entries(load_ledger(), bh, sq, sk, d,
                            _norm_dtype(dtype), causal, dk)
    return bool(iso and iso.get("fused_epilogue_wins"))


def packed_grid_enabled(platform: Optional[str] = None) -> bool:
    """Resolve FLAGS_flash_packed_grid for the current device.

    'auto' (the shipped default): ON under the Pallas interpreter (the
    packing is numerically exact there — pinned by tier-1), and on real
    TPUs only when the baked ledger marks packed_grid_validated for this
    device_kind (the non-affine index maps have never lowered on
    hardware; r5's validation probe died with the tunnel)."""
    v = _flags.flag_value("flash_packed_grid")
    if isinstance(v, bool):
        return v
    s = str(v).lower()
    if s in ("1", "true", "on", "yes"):
        return True
    if s in ("0", "false", "off", "no"):
        return False
    # auto
    try:
        import jax
        on_tpu = jax.default_backend() == "tpu" and platform != "cpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        return True
    led = load_ledger()
    return bool(led and led.get("packed_grid_validated")
                and led.get("device_kind") == _device_kind(platform))


# --------------------------------------------------------------------------
# measurement fallback
# --------------------------------------------------------------------------

# deterministic roofline constants for the CPU proxy. eff_* are MXU
# utilization fractions: dense pinned to the r5 on-TPU measurement
# (~13.4/197); flash assumes the bf16-operand rewrite reaches the same
# MXU mode as the dense einsum (the whole point of the rewrite) — an
# explicit HYPOTHESIS until hardware numbers exist, and labeled so.
_PROXY = {"peak_flops": 197e12, "eff_dense": 0.068, "eff_flash": 0.068,
          "hbm_bps": 820e9}


def _proxy_ms(kind, bh, sq, sk, d, dtype, causal, backend,
              packed: bool) -> float:
    """Analytic max(compute, memory) time in ms. Deterministic: pure
    arithmetic on the shape key, no clocks, no randomness."""
    nbytes = 2 if dtype == "bfloat16" else 4
    fwd_flops = 4.0 * bh * sq * sk * d            # QK^T + PV
    io = bh * (sq + 2 * sk) * d * nbytes + bh * sq * d * nbytes
    if kind == "bwd":
        fwd_flops *= 2.5                          # dS, dQ, dK, dV dots
        io *= 2.0
    if backend == "pallas":
        flops = fwd_flops * (0.5 if (causal and packed) else 1.0)
        t = max(flops / (_PROXY["peak_flops"] * _PROXY["eff_flash"]),
                io / _PROXY["hbm_bps"])
    else:
        # dense materializes the (sq, sk) f32 scores at least once
        # (write + read through softmax); the remat backward pays it
        # again on the recompute
        s2 = bh * sq * sk * 4.0 * (3.0 if kind == "bwd" else 2.0)
        t = max(fwd_flops / (_PROXY["peak_flops"] * _PROXY["eff_dense"]),
                (io + s2) / _PROXY["hbm_bps"])
    return t * 1e3


def _measure_tpu(bh, sq, sk, d, dtype, causal):
    """Real flash-vs-dense timing on a reachable TPU (scan-amortized, 8
    iters per dispatch — per-call timing through the tunnel ranks by
    queue noise). Returns {(kind, backend): ms} or None on any failure."""
    try:
        import jax
        import jax.numpy as jnp
        from .flash_attention import (_flash_fwd_bhsd, _flash_bwd_bhsd,
                                      _xla_attention_bhsd)
        tb = min(bh, 64)
        jdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        q = jnp.zeros((tb, sq, d), jdt)
        k = jnp.zeros((tb, sk, d), jdt)
        v = jnp.zeros((tb, sk, d), jdt)

        import time as _time

        def _timed(step):
            @jax.jit
            def loop():
                def body(c, _):
                    s = step(q + c)
                    return (s * 0).astype(q.dtype), None
                c, _ = jax.lax.scan(body, jnp.zeros((), q.dtype), None,
                                    length=8)
                return c
            jax.block_until_ready(loop())   # compile + warm
            best = float("inf")
            for _ in range(2):
                t0 = _time.perf_counter()
                jax.block_until_ready(loop())
                best = min(best, _time.perf_counter() - t0)
            return best / 8 * 1e3

        out = {}
        out[("fwd", "pallas")] = _timed(lambda qq: jnp.sum(
            _flash_fwd_bhsd(qq, k, v, causal, 1.0)[0].astype(jnp.float32)))
        out[("fwd", "xla")] = _timed(lambda qq: jnp.sum(
            _xla_attention_bhsd(qq, k, v, causal, 1.0).astype(jnp.float32)))
        o, lse = _flash_fwd_bhsd(q, k, v, causal, 1.0)
        jax.block_until_ready(o)
        out[("bwd", "pallas")] = _timed(lambda qq: sum(
            jnp.sum(x.astype(jnp.float32)) for x in _flash_bwd_bhsd(
                qq, k, v, o, lse, o, causal, 1.0)))

        def _dense_grad(qq):
            g = jax.grad(lambda a: jnp.sum(_xla_attention_bhsd(
                a, k, v, causal, 1.0).astype(jnp.float32)))(qq)
            return jnp.sum(g.astype(jnp.float32))
        out[("bwd", "xla")] = _timed(_dense_grad)
        return out
    except Exception:
        return None


def _heuristic(bh, sq, sk, d) -> str:
    """The legacy _use_pallas thresholds (calibrated to the r4/r5
    f32-operand kernels; kept only as the last-resort fallback)."""
    if d % 128 == 0:
        return "pallas" if sq >= 1024 else "xla"
    return "pallas" if (d >= 96 and sq >= 2048) else "xla"


# --------------------------------------------------------------------------
# the router
# --------------------------------------------------------------------------

def route(batch_heads: int, seq_q: int, seq_k: int, head_dim: int, dtype,
          causal: bool, platform: Optional[str] = None,
          device_kind: Optional[str] = None) -> Decision:
    """Resolve the attention backend for one shape key.

    batch_heads = batch * num_query_heads (the flash grid's parallel
    axis).  platform/device_kind default to the live jax backend; tests
    pass them explicitly to route for a device they are not running on.
    Decisions are cached per (key, ledger path, mode flag)."""
    dtype = _norm_dtype(dtype)
    mode = _flags.flag_value("attention_router")
    dk = device_kind or _device_kind(platform)
    plat = platform or ("tpu" if dk.lower().startswith("tpu") else "cpu")
    key = (batch_heads, seq_q, seq_k, head_dim, dtype, bool(causal),
           plat, dk, _ledger_path(), mode)
    hit = _route_cache.get(key)
    if hit is not None:
        return hit

    packed = packed_grid_enabled(plat)
    dec = None

    if mode != "heuristic":
        led = load_ledger()
        e2e, iso = _match_entries(led, batch_heads, seq_q, seq_k, head_dim,
                                  dtype, causal, dk)
        if e2e is not None:
            dec = Decision(
                fwd=e2e.get("fwd", "pallas"), bwd=e2e.get("bwd", "pallas"),
                blocks_fwd=tuple(iso["blocks_fwd"]) if iso and
                iso.get("blocks_fwd") else None,
                blocks_bwd=tuple(iso["blocks_bwd"]) if iso and
                iso.get("blocks_bwd") else None,
                packed_grid=packed, source="ledger-e2e",
                provenance=(
                    f"ledger v{led.get('version')} r{led.get('round')} "
                    f"end-to-end [{e2e.get('config')}] on "
                    f"{led.get('device_kind')}: fwd={e2e.get('fwd')} "
                    f"bwd={e2e.get('bwd')} ({e2e.get('note', 'measured')})"))
        elif iso is not None:
            dec = Decision(
                fwd=iso.get("fwd", "pallas"), bwd=iso.get("bwd", "pallas"),
                blocks_fwd=tuple(iso["blocks_fwd"]) if
                iso.get("blocks_fwd") else None,
                blocks_bwd=tuple(iso["blocks_bwd"]) if
                iso.get("blocks_bwd") else None,
                packed_grid=packed, source="ledger",
                provenance=(
                    f"ledger v{led.get('version')} r{led.get('round')} "
                    f"measured on {led.get('device_kind')} at bh="
                    f"{iso.get('bh')}: fwd={iso.get('fwd')} "
                    f"({json.dumps(iso.get('fwd_ms', {}))}) "
                    f"bwd={iso.get('bwd')} "
                    f"({json.dumps(iso.get('bwd_ms', {}))})"))

    if dec is None and mode == "auto":
        if plat == "tpu":
            ms = _measure_tpu(batch_heads, seq_q, seq_k, head_dim, dtype,
                              causal)
            if ms is not None:
                fwd = min(("pallas", "xla"),
                          key=lambda b: ms[("fwd", b)])
                bwd = min(("pallas", "xla"),
                          key=lambda b: ms[("bwd", b)])
                dec = Decision(
                    fwd=fwd, bwd=bwd, packed_grid=packed,
                    source="measured-tpu",
                    provenance=("measured live on "
                                f"{dk} (ledger miss): "
                                + json.dumps({f"{k[0]}_{k[1]}":
                                              round(v, 3)
                                              for k, v in ms.items()})))
        else:
            est = {(k, b): _proxy_ms(k, batch_heads, seq_q, seq_k,
                                     head_dim, dtype, causal, b, packed)
                   for k in ("fwd", "bwd") for b in ("pallas", "xla")}
            fwd = min(("pallas", "xla"), key=lambda b: est[("fwd", b)])
            bwd = min(("pallas", "xla"), key=lambda b: est[("bwd", b)])
            dec = Decision(
                fwd=fwd, bwd=bwd, packed_grid=packed, source="proxy",
                provenance=("analytic roofline proxy (no TPU reachable; "
                            "NOT a measurement — assumes the bf16-operand "
                            "kernels reach dense-einsum MXU efficiency): "
                            + json.dumps({f"{k[0]}_{k[1]}": round(v, 3)
                                          for k, v in est.items()})))

    if dec is None:
        b = _heuristic(batch_heads, seq_q, seq_k, head_dim)
        dec = Decision(fwd=b, bwd="pallas", packed_grid=packed,
                       source="heuristic",
                       provenance=("legacy seq/head_dim thresholds "
                                   "(calibrated to the retired f32-operand "
                                   "kernels; no ledger entry, measurement "
                                   "unavailable)"))

    _route_cache[key] = dec
    _decision_log.append((key[:6], dec))
    del _decision_log[:-256]  # bound the audit log
    try:
        # the structured successor of the audit list: every FRESH decision
        # (cache hits excluded) counted by source, exported with the rest
        # of the registry — bench rows and the serving engine read these
        from ...observability.catalog import metric as _obs_metric
        _obs_metric("attention_router_decisions_total",
                    source=dec.source).inc()
    except Exception:  # noqa: BLE001 — routing must never fail on telemetry
        pass
    return dec
