"""Flash attention forward as a Pallas TPU kernel.

reference capability: paddle/phi/kernels/gpu/flash_attn_kernel.cu
(FlashAttention-2 via dynload) + python/paddle/nn/functional/flash_attention.py.

TPU-native design (not a CUDA port):
- Grid over (batch*heads, q_blocks); K/V for the (batch, head) live in VMEM
  (fits to ~8k sequence at head_dim 128 in bf16), the q block streams
  through the online-softmax loop over K blocks — the classic
  numerically-stable running (m, l, acc) recurrence.
- MXU does the two matmuls per block with fp32 accumulation
  (preferred_element_type); VPU does the softmax pieces.
- Causal: K blocks strictly above the diagonal are skipped via @pl.when
  (no wasted FLOPs), the diagonal block is masked with broadcasted_iota.
- Backward: jax.custom_vjp whose bwd rematerializes through the XLA
  attention (jax.checkpoint-style) — fwd gets the handwritten kernel,
  bwd gets XLA's fused flash-style backward. A handwritten bwd kernel is
  a later optimization, not a correctness requirement.

On non-TPU backends the kernel runs under the Pallas interpreter (tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
               scale: float, seq_k: int, block_q: int, mask_k_tail: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    d = q.shape[-1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = pl.cdiv(seq_k, block_k)

    def body(j, carry):
        m, l, acc = carry

        def compute():
            k = k_ref[0, pl.ds(j * block_k, block_k), :]
            v = v_ref[0, pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (block_q, block_k)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if mask_k_tail:
                # K/V are padded to a block multiple: mask padded columns
                s = jnp.where(cols < seq_k, s, NEG_INF)
            if causal:
                rows = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                s = jnp.where(rows >= cols, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p, v.astype(jnp.float32),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        if causal:
            # skip blocks strictly above the diagonal of this q block
            needed = (j * block_k) <= (qi * block_q + block_q - 1)
            return jax.lax.cond(needed, compute, lambda: (m, l, acc))
        return compute()

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x, axis, multiple):
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


def _flash_fwd_bhsd(q, k, v, causal, scale, block_q=128, block_k=128,
                    interpret=None):
    """q/k/v: (BH, S, D). Ragged sequence lengths are padded to block
    multiples; padded K columns are masked in-kernel, padded Q rows sliced
    off on return (so results are exact for any length)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    q_p = _pad_to(q, 1, block_q)
    k_p = _pad_to(k, 1, block_k)
    v_p = _pad_to(v, 1, block_k)
    sq_p, sk_p = q_p.shape[1], k_p.shape[1]
    mask_k_tail = sk_p != sk
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    grid = (bh, sq_p // block_q)
    kernel = functools.partial(_fa_kernel, block_k=block_k, causal=causal,
                               scale=scale, seq_k=sk, block_q=block_q,
                               mask_k_tail=mask_k_tail)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk_p, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
        interpret=interpret,
    )(q_p, k_p, v_p)
    return out[:, :sq]


def _xla_attention_bhsd(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_bhsd(q, k, v, causal, scale):
    return _flash_fwd_bhsd(q, k, v, causal, scale)


def _fa_fwd(q, k, v, causal, scale):
    return _flash_fwd_bhsd(q, k, v, causal, scale), (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp_fn = jax.vjp(lambda q_, k_, v_: _xla_attention_bhsd(
        q_, k_, v_, causal, scale), q, k, v)
    return vjp_fn(g)


_flash_attention_bhsd.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """Paddle flash_attention layout: (batch, seq, heads, head_dim)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)
    out = _flash_attention_bhsd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)
