"""Flash attention (forward + backward) as Pallas TPU kernels.

reference capability: paddle/phi/kernels/gpu/flash_attn_kernel.cu and
flash_attn_grad_kernel.cu (FlashAttention-2 via dynload) +
python/paddle/nn/functional/flash_attention.py.

TPU-native design (not a CUDA port):
- Forward: grid (batch*heads, q_blocks, k_blocks). Q/K/V blocks are DMA'd
  per grid step by BlockSpec — no whole-K/V-in-VMEM residency, so sequence
  length is bounded by HBM, not VMEM. The online-softmax running
  (m, l, acc) state lives in VMEM scratch that persists across the
  (sequential, innermost) k-block grid dimension. The forward also emits
  the per-row logsumexp for the backward.
- Backward: the FlashAttention-2 split. delta = rowsum(dO * O) is a cheap
  XLA elementwise reduce. dQ kernel: grid (bh, q_blocks, k_blocks),
  accumulates scale * dS @ K into VMEM scratch. dK/dV kernel: grid
  (bh, k_blocks, q_blocks), accumulates dS^T @ Q and P^T @ dO. P is
  rematerialized per block from (Q, K, lse) — nothing O(S^2) is ever
  stored.
- MXU does the matmuls with fp32 accumulation (preferred_element_type);
  VPU does the softmax pieces. Causal: blocks strictly above the diagonal
  skip compute via @pl.when; the diagonal block is masked with
  broadcasted_iota. Cross-length causal uses the bottom-right-aligned
  convention (offset = seq_k - seq_q), matching the dense reference.

On non-TPU backends the kernels run under the Pallas interpreter (tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...framework import flags as _flags

NEG_INF = -1e30
_LANES = 128  # store per-row scalars broadcast across one lane tile


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows + offset >= cols, s, NEG_INF)


def _ktail_mask(s, kj, block_q, block_k, seq_k):
    cols = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(cols < seq_k, s, NEG_INF)


def _block_needed(qi, kj, block_q, block_k, causal, offset):
    if not causal:
        return True
    # any (row, col) with row + offset >= col in this block pair?
    return (qi * block_q + block_q - 1 + offset) >= (kj * block_k)


_flags.define_flag(
    "flash_packed_grid", "auto",
    "causal flash kernels iterate only the lower-triangle (q,k) block "
    "pairs instead of a rectangular grid with half the steps masked off "
    "(saves the skipped steps' k/v DMAs and grid overhead). 'auto' (the "
    "default since the bf16 finalization): ON under the Pallas "
    "interpreter (numerically exact, pinned by tier-1) and on real TPUs "
    "only when the baked attention ledger marks packed_grid_validated "
    "for the device — the non-affine index maps have never lowered on "
    "hardware (the r5 probe died with the tunnel), so the ledger flips "
    "this per-device once .tpu_queue/451_packed_ab.sh proves it. "
    "on/off force it either way. NOTE: read at TRACE time — set the env "
    "var before process start (or clear jit caches); set_flags after a "
    "shape compiled does not retrace it.")


def _packing_on():
    from .attention_router import packed_grid_enabled
    return packed_grid_enabled()


def _tri_decode(p):
    """Linear triangle index -> (qi, kj) with kj <= qi (row-major packing:
    p = qi*(qi+1)/2 + kj). The causal-packed grid iterates ONLY the lower
    triangle of (q block, k block) pairs — a full rectangular grid spends
    half its steps (and their k/v block DMAs) on pairs the causal mask
    fully discards. f32 sqrt is exact for the sizes involved (p < 2^23);
    the +-1 correction guards the perfect-square boundary cases."""
    pf = p.astype(jnp.float32)
    qi = jnp.floor((jnp.sqrt(8.0 * pf + 1.0) - 1.0) * 0.5).astype(jnp.int32)
    tri = qi * (qi + 1) // 2
    qi = jnp.where(p < tri, qi - 1, qi)
    qi = jnp.where(p >= (qi + 1) * (qi + 2) // 2, qi + 1, qi)
    kj = p - qi * (qi + 1) // 2
    return qi, kj


def _tri_maps(g):
    """(qmap, kmap) BlockSpec index maps for the packed (bh, tri) grid —
    shared by the fwd and dQ kernels (the dKV kernel's reversed-row
    staircase variant lives at its call site)."""
    def qmap(b, p):
        qi, _ = _tri_decode(p)
        return (b, qi, 0)

    def kmap(b, p):
        _, kj = _tri_decode(p)
        return (b // g, kj, 0)
    return qmap, kmap


def _fa_fwd_kernel(q_ref, k_ref, v_ref, *refs,
                   causal: bool, scale: float, seq_k: int, block_q: int,
                   block_k: int, offset: int, mask_k_tail: bool,
                   packed: bool = False, epilogue: bool = False,
                   rms_eps: float = 1e-6, rms_d: int = 0):
    # optional fused epilogue (FlashFuser-style widened fusion): two extra
    # inputs — residual block + lane-broadcast RMSNorm gamma — and the
    # flush writes rmsnorm(attn + residual) * gamma instead of attn,
    # saving one full HBM round-trip of the attention output. The norm
    # axis is the head dim (rms_d = TRUE d, so zero-pad columns don't
    # skew the mean).
    if epilogue:
        res_ref, w_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
    else:
        o_ref, lse_ref, m_s, l_s, acc_s = refs
    if packed:   # causal lower-triangle grid: (bh, tri(nq))
        qi, kj = _tri_decode(pl.program_id(1))
        is_last = kj == qi   # kj_max(qi) == qi when block_q == block_k
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        is_last = kj == pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    def _compute():
        # dots run on NATIVE (bf16) operands with f32 accumulation — the
        # MXU's full-rate mode and exactly the dense XLA path's precision
        # (einsum + preferred_element_type=f32). Upcasting operands to
        # f32 first quarters MXU throughput; r5 measured the f32-operand
        # flavor of this kernel at 0.86x dense fwd / 0.52x dense bwd.
        q = q_ref[0]                              # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if mask_k_tail:
            s = _ktail_mask(s, kj, block_q, block_k, seq_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        m_prev = m_s[...][:, :1]
        l_prev = l_s[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    if causal and not packed:
        pl.when(_block_needed(qi, kj, block_q, block_k, causal, offset))(
            _compute)
    else:
        _compute()   # packed grid contains only needed blocks

    @pl.when(is_last)
    def _flush():
        l = jnp.maximum(l_s[...][:, :1], 1e-30)
        out = acc_s[...] / l
        if epilogue:
            h = out + res_ref[0].astype(jnp.float32)
            # mean over the TRUE head dim (pad columns are zero in both
            # attn out and residual, so the sum is exact)
            ms = jnp.sum(h * h, axis=-1, keepdims=True) / rms_d
            out = h * jax.lax.rsqrt(ms + rms_eps) * \
                w_ref[...][:1, :].astype(jnp.float32)
        o_ref[0] = out.astype(o_ref.dtype)
        # lane-expanded (block_q, _LANES) write: TPU block shapes need the
        # last two dims tiled (8, 128); a (1, block_q) row per grid step is
        # unlowerable. m_s/l_s already hold the row value in every lane.
        # (Same layout as jax's official TPU flash kernel's l/m outputs.)
        lse_ref[0] = m_s[...] + jnp.log(jnp.maximum(l_s[...], 1e-30))


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                  dq_s, *, causal: bool, scale: float, seq_k: int,
                  block_q: int, block_k: int, offset: int,
                  mask_k_tail: bool, packed: bool = False):
    if packed:   # causal lower-triangle grid: (bh, tri(nq))
        qi, kj = _tri_decode(pl.program_id(1))
        is_last = kj == qi
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        is_last = kj == pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    def _compute():
        # bf16 operands + f32 accumulation on every dot (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                   # (block_q, 1) of lanes
        delta = delta_ref[0][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask_k_tail:
            s = _ktail_mask(s, kj, block_q, block_k, seq_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)                      # (block_q, block_k)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_s[...] += scale * jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal and not packed:
        pl.when(_block_needed(qi, kj, block_q, block_k, causal, offset))(
            _compute)
    else:
        _compute()   # packed grid contains only needed blocks

    @pl.when(is_last)
    def _flush():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_s, dv_s, *, causal: bool, scale: float,
                   seq_k: int, block_q: int, block_k: int, offset: int,
                   mask_k_tail: bool, n_rep: int = 1, packed_nq: int = 0):
    # grid (bh_kv, k blocks, q-head group reps, q blocks): the scratch
    # accumulates over BOTH the group axis and the q blocks, flushing once
    # per kv block — this is how GQA's dK/dV reduction happens in-kernel.
    # Packed (causal, square blocks): grid (bh_kv, tri(nq), reps) where the
    # triangle index runs (kj, qi >= kj) pairs via u = nq-1-kj, w = qi-kj
    # (so per-kj pairs are consecutive and the scratch flushes per kv block)
    if packed_nq:
        u, w = _tri_decode(pl.program_id(1))
        kj = packed_nq - 1 - u
        qi = kj + w
        rr = pl.program_id(2)
        first = (w == 0) & (rr == 0)
        last = (w == u) & (rr == n_rep - 1)
    else:
        kj = pl.program_id(1)
        rr = pl.program_id(2)
        qi = pl.program_id(3)
        first = (qi == 0) & (rr == 0)
        last = (qi == pl.num_programs(3) - 1) & (rr == n_rep - 1)

    @pl.when(first)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    def _compute():
        # bf16 operands + f32 accumulation on every dot (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask_k_tail:
            s = _ktail_mask(s, kj, block_q, block_k, seq_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        p_lo = p.astype(do.dtype)
        dv_s[...] += jax.lax.dot_general(
            p_lo, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_k, d)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_s[...] += scale * jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal and not packed_nq:
        pl.when(_block_needed(qi, kj, block_q, block_k, causal, offset))(
            _compute)
    else:
        _compute()   # packed grid contains only needed blocks

    @pl.when(last)
    def _flush():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _pad_to(x, axis, multiple):
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


def _interpret_default():
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-manual-axes: inside a
    new-style shard_map (check_vma), pallas_call outputs must declare how
    they vary over the mesh (e.g. the ring-attention 'sep' axis)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _block_sizes(sq, sk, block_q, block_k):
    return min(block_q, sq), min(block_k, sk)


# candidate (block_q, block_k) VMEM tilings for the autotuner — the TPU
# analog of the reference's per-algorithm candidate list (auto_tune_base.h).
# Large tiles are cheap in VMEM (512x512: ~1.3MB of block buffers vs the
# ~128MB budget) and cut grid-iteration overhead 8-16x vs 128x128.
_BLOCK_CANDIDATES = ((128, 128), (256, 128), (128, 256), (256, 256),
                     (512, 128), (128, 512), (256, 512), (512, 256),
                     (512, 512))


# Shipped block-size table keyed by (kind, seq bucket, head_dim),
# consulted when autotune is off so production gets measured tiles
# without paying a tuning pass. Populate from a hardware autotune run:
# tools/flash_vs_xla.py (on the TPU queue) then tools/bake_flash_blocks.py
# prints the literal. Empty or missing entries fall back to (128, 128).
_SHIPPED_BLOCKS = {}


def _shipped_blocks(kind, sq, d, device_kind):
    if "v5 lite" not in device_kind:
        return None
    bucket = 1024 if sq <= 1024 else (2048 if sq <= 2048 else 4096)
    return _SHIPPED_BLOCKS.get((kind, bucket, d))


def _tuned_blocks(kind, bh, sq, sk, d, dtype, causal, interpret):
    """Resolve (block_q, block_k): the baked attention ledger (versioned,
    device-tagged — tools/bake_flash_blocks.py --ledger), the legacy
    _SHIPPED_BLOCKS literal, the runtime-timed winner when
    FLAGS_use_autotune is on, else (128, 128). Timing runs on synthetic
    zeros, so this works even while the caller is being traced."""
    from .autotune import autotune, autotune_enabled
    if not autotune_enabled():
        if not interpret:
            from .attention_router import ledger_blocks
            hit = ledger_blocks(kind, bh, sq, sk, d, dtype, causal)
            if hit:
                return hit
        if _SHIPPED_BLOCKS and not interpret:
            hit = _shipped_blocks(kind, sq, d,
                                  getattr(jax.devices()[0], "device_kind", ""))
            if hit and hit[0] <= sq and hit[1] <= sk:
                return hit
        return 128, 128
    dev = jax.devices()[0]
    # tb (the clamped tuning batch*heads) is part of the key: block ranking
    # depends on grid parallelism, so a winner timed at 2 heads must not be
    # served to a 64-head caller
    tb = min(bh, 64)
    key = (kind, tb, sq, sk, d, str(dtype), bool(causal), dev.device_kind)

    def make_runner(cfg):
        bq, bk = cfg
        if bq > sq or bk > sk:
            raise ValueError("block larger than sequence")
        # tune at (close to) the caller's real batch*heads: block choice
        # interacts with grid parallelism, and a 2-head proxy ranked
        # candidates differently from the bh=64 train shape on v5e
        q = jnp.zeros((tb, sq, d), dtype)
        k = jnp.zeros((tb, sk, d), dtype)
        v = jnp.zeros((tb, sk, d), dtype)
        # each candidate runs 8 iterations inside ONE compiled scan: a
        # single dispatch through the axon tunnel costs ~65ms, so per-call
        # timing ranks candidates by queue noise, not kernel speed (the r5
        # first-pass autotune table proved it). The carry feeds q so the
        # body can't be hoisted.
        if kind == "fwd":
            def step(qq):
                o, _ = _flash_fwd_bhsd(qq, k, v, causal, 1.0, block_q=bq,
                                       block_k=bk, interpret=interpret)
                return jnp.sum(o.astype(jnp.float32))
        else:
            o, lse = _flash_fwd_bhsd(q, k, v, causal, 1.0, block_q=bq,
                                     block_k=bk, interpret=interpret)
            jax.block_until_ready(o)

            def step(qq):
                outs = _flash_bwd_bhsd(qq, k, v, o, lse, o, causal, 1.0,
                                       block_q=bq, block_k=bk,
                                       interpret=interpret)
                return sum(jnp.sum(x.astype(jnp.float32)) for x in outs)

        @jax.jit
        def loop():
            def body(c, _):
                s = step(q + c)
                return (s * 0).astype(q.dtype), None
            c, _ = jax.lax.scan(body, jnp.zeros((), q.dtype), None, length=8)
            return c

        def run():
            jax.block_until_ready(loop())
        return run

    return autotune(key, _BLOCK_CANDIDATES, make_runner, default=(128, 128))


def _flash_fwd_bhsd(q, k, v, causal, scale, block_q=128, block_k=128,
                    interpret=None, q_per_kv=1, residual=None,
                    rms_weight=None, rms_eps=1e-6, rms_d=None):
    """q: (BH, Sq, D), k/v: (BH // q_per_kv, Sk, D) -> (out, lse).

    residual/rms_weight (both given or neither): fuse the
    rmsnorm(attn + residual) * weight epilogue into the kernel's flush —
    the attention output never round-trips HBM unnormalized. residual:
    (BH, Sq, D); rms_weight: (D,). rms_d = the TRUE head dim when D is
    zero-padded (the mean divisor). Forward-only (no VJP).

    Ragged sequence lengths are padded to block multiples; padded K columns
    are masked in-kernel, padded Q rows sliced off on return (so results
    are exact for any length).

    GQA (q_per_kv > 1): kv stays UNEXPANDED — the k/v BlockSpec index map
    folds the head grouping (q index b -> kv index b // q_per_kv), so no
    (B, S, H, D) broadcast of KV ever materializes in HBM. With batch-major
    bh layout (bi*h + hq), b // q_per_kv == bi*kvh + hq // rep exactly."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _block_sizes(sq, sk, block_q, block_k)
    q_p = _pad_to(q, 1, block_q)
    k_p = _pad_to(k, 1, block_k)
    v_p = _pad_to(v, 1, block_k)
    sq_p, sk_p = q_p.shape[1], k_p.shape[1]
    mask_k_tail = sk_p != sk
    if interpret is None:
        interpret = _interpret_default()
    g = q_per_kv
    nq, nk = sq_p // block_q, sk_p // block_k
    # causal + square blocks + equal (padded) lengths: pack the grid to
    # the lower triangle of (q block, k block) pairs — the rectangular
    # grid spends half its steps and k/v DMAs on fully-masked pairs
    packed = (causal and sk == sq and sq_p == sk_p
              and block_q == block_k and _packing_on())
    epilogue = residual is not None
    kernel = functools.partial(
        _fa_fwd_kernel, causal=causal, scale=scale, seq_k=sk,
        block_q=block_q, block_k=block_k, offset=sk - sq,
        mask_k_tail=mask_k_tail, packed=packed, epilogue=epilogue,
        rms_eps=rms_eps, rms_d=(rms_d or d))
    if packed:
        grid = (bh, nq * (nq + 1) // 2)
        qmap, kmap = _tri_maps(g)
        in_maps = [qmap, kmap, kmap]
        out_maps = [qmap, qmap]
        wmap = lambda b, p: (0, 0)   # noqa: E731
    else:
        grid = (bh, nq, nk)
        in_maps = [lambda b, i, j: (b, i, 0),
                   lambda b, i, j: (b // g, j, 0),
                   lambda b, i, j: (b // g, j, 0)]
        out_maps = [lambda b, i, j: (b, i, 0), lambda b, i, j: (b, i, 0)]
        wmap = lambda b, i, j: (0, 0)   # noqa: E731
    in_specs = [
        pl.BlockSpec((1, block_q, d), in_maps[0]),
        pl.BlockSpec((1, block_k, d), in_maps[1]),
        pl.BlockSpec((1, block_k, d), in_maps[2]),
    ]
    operands = [q_p, k_p, v_p]
    if epilogue:
        # residual rides the q index map; gamma is one (8, d) sublane-
        # tiled block (a bare (1, d) block is unlowerable on TPU), f32 so
        # bf16 gammas don't hit the (16, 128) bf16 tile minimum
        in_specs.append(pl.BlockSpec((1, block_q, d), in_maps[0]))
        in_specs.append(pl.BlockSpec((8, d), wmap))
        operands.append(_pad_to(residual, 1, block_q))
        operands.append(jnp.broadcast_to(
            rms_weight.astype(jnp.float32)[None, :], (8, d)))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), out_maps[0]),
            pl.BlockSpec((1, block_q, _LANES), out_maps[1]),
        ],
        out_shape=[
            _sds((bh, sq_p, d), q.dtype, q),
            _sds((bh, sq_p, _LANES), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    # collapse the lane-expanded lse back to (bh, sq_p) right away so the
    # autodiff residual is O(S), not O(S * 128)
    return out[:, :sq], lse[..., 0]


def _flash_bwd_bhsd(q, k, v, o, lse, g, causal, scale, block_q=128,
                    block_k=128, interpret=None, q_per_kv=1):
    """FlashAttention-2 backward: returns (dq, dk, dv), all in input dtype.
    GQA: k/v carry BH // q_per_kv heads; dk/dv come back already reduced
    over the query-head group (the rep axis rides the grid, accumulating
    into the same VMEM scratch — no XLA-side segment-sum needed)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _block_sizes(sq, sk, block_q, block_k)
    if interpret is None:
        interpret = _interpret_default()

    # delta = rowsum(dO * O): cheap XLA elementwise+reduce, fp32
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    q_p = _pad_to(q, 1, block_q)
    do_p = _pad_to(g, 1, block_q)
    delta_p = _pad_to(delta, 1, block_q)
    k_p = _pad_to(k, 1, block_k)
    v_p = _pad_to(v, 1, block_k)
    sq_p, sk_p = q_p.shape[1], k_p.shape[1]
    # lse from the forward is already padded to a block_q multiple of the
    # forward's padding; re-pad defensively (values for pad rows are finite,
    # and pad-row contributions vanish because dO pad rows are zero).
    lse_p = _pad_to(lse, 1, block_q)[:, :sq_p]
    mask_k_tail = sk_p != sk
    offset = sk - sq
    common = dict(causal=causal, scale=scale, seq_k=sk, block_q=block_q,
                  block_k=block_k, offset=offset, mask_k_tail=mask_k_tail)

    nq, nk = sq_p // block_q, sk_p // block_k

    # lane-expand the per-row scalars: a (1, block_q) block is unlowerable
    # on TPU (last-two-dims tiling), so feed (1, block_q, _LANES) blocks
    lse3 = jnp.broadcast_to(lse_p[..., None], (bh, sq_p, _LANES))
    delta3 = jnp.broadcast_to(delta_p[..., None], (bh, sq_p, _LANES))

    grp = q_per_kv
    bh_kv = bh // grp
    # same lower-triangle packing as the forward (see _flash_fwd_bhsd):
    # dq accumulates over kj <= qi only, so the rectangular grid's upper
    # half is pure skipped-step overhead for causal self-attention
    packed = (causal and sk == sq and sq_p == sk_p
              and block_q == block_k and _packing_on())
    if packed:
        dq_grid = (bh, nq * (nq + 1) // 2)
        dq_qmap, dq_kmap = _tri_maps(grp)
        dq_in = [dq_qmap, dq_kmap, dq_kmap, dq_qmap, dq_qmap, dq_qmap]
        dq_out = dq_qmap
    else:
        dq_grid = (bh, nq, nk)
        dq_qm = lambda b, i, j: (b, i, 0)   # noqa: E731
        dq_km = lambda b, i, j: (b // grp, j, 0)   # noqa: E731
        dq_in = [dq_qm, dq_km, dq_km, dq_qm, dq_qm, dq_qm]
        dq_out = dq_qm
    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel, packed=packed, **common),
        grid=dq_grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), dq_in[0]),
            pl.BlockSpec((1, block_k, d), dq_in[1]),
            pl.BlockSpec((1, block_k, d), dq_in[2]),
            pl.BlockSpec((1, block_q, d), dq_in[3]),
            pl.BlockSpec((1, block_q, _LANES), dq_in[4]),
            pl.BlockSpec((1, block_q, _LANES), dq_in[5]),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), dq_out),
        out_shape=_sds((bh, sq_p, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse3, delta3)

    # dkv grid: (kv heads, kv blocks, group reps, q blocks) — i innermost,
    # then r, so for a fixed kv block the scratch accumulates over the
    # whole query-head group before flushing (n_rep=grp in the kernel).
    # Packed: (kv heads, tri(nq), reps) — see _fa_dkv_kernel
    if packed:
        def dkv_qmap(b, p, r):
            u, w = _tri_decode(p)
            return (b * grp + r, (nq - 1 - u) + w, 0)

        def dkv_kmap(b, p, r):
            u, _ = _tri_decode(p)
            return (b, nq - 1 - u, 0)
        dkv_grid = (bh_kv, nq * (nq + 1) // 2, grp)
        dkv_in = [dkv_qmap, dkv_kmap, dkv_kmap, dkv_qmap, dkv_qmap,
                  dkv_qmap]
        dkv_out = dkv_kmap
        dkv_extra = {"packed_nq": nq}
    else:
        dkv_qm = lambda b, j, r, i: (b * grp + r, i, 0)   # noqa: E731
        dkv_km = lambda b, j, r, i: (b, j, 0)   # noqa: E731
        dkv_grid = (bh_kv, nk, grp, nq)
        dkv_in = [dkv_qm, dkv_km, dkv_km, dkv_qm, dkv_qm, dkv_qm]
        dkv_out = dkv_km
        dkv_extra = {}
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel, n_rep=grp, **dkv_extra, **common),
        grid=dkv_grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), dkv_in[0]),
            pl.BlockSpec((1, block_k, d), dkv_in[1]),
            pl.BlockSpec((1, block_k, d), dkv_in[2]),
            pl.BlockSpec((1, block_q, d), dkv_in[3]),
            pl.BlockSpec((1, block_q, _LANES), dkv_in[4]),
            pl.BlockSpec((1, block_q, _LANES), dkv_in[5]),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), dkv_out),
            pl.BlockSpec((1, block_k, d), dkv_out),
        ],
        out_shape=[
            _sds((bh_kv, sk_p, d), k.dtype, k),
            _sds((bh_kv, sk_p, d), v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse3, delta3)

    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


def _xla_attention_bhsd(q, k, v, causal, scale):
    """Dense reference (O(S^2) memory). Used by tests and tiny shapes."""
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


def _fwd_blocks(q, k, causal):
    bh, sq, d = q.shape
    return _tuned_blocks("fwd", bh, sq, k.shape[1], d, q.dtype, causal,
                         _interpret_default())


def _bwd_blocks(q, k, causal):
    bh, sq, d = q.shape
    return _tuned_blocks("bwd", bh, sq, k.shape[1], d, q.dtype, causal,
                         _interpret_default())


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_bhsd(q, k, v, causal, scale, q_per_kv=1):
    bq, bk = _fwd_blocks(q, k, causal)
    out, _ = _flash_fwd_bhsd(q, k, v, causal, scale, block_q=bq, block_k=bk,
                             q_per_kv=q_per_kv)
    return out


def _fa_fwd(q, k, v, causal, scale, q_per_kv=1):
    bq, bk = _fwd_blocks(q, k, causal)
    out, lse = _flash_fwd_bhsd(q, k, v, causal, scale, block_q=bq, block_k=bk,
                               q_per_kv=q_per_kv)
    return out, (q, k, v, out, lse)


def _dense_remat_bwd(q, k, v, causal, scale, q_per_kv, g):
    """Backward via XLA-dense rematerialization (GQA-grouped).

    Measured on TPU v5e (r5): ISOLATED-kernel timing favors this hybrid
    over the Pallas dQ/dKV split (9.0ms vs 12.9ms fwd+bwd at s2048 d128
    with the f32-operand kernels), but END-TO-END the 535m train step
    measured the opposite — 0.406 MFU hybrid vs 0.426 full-pallas — the
    transient (bh, sq, sk) fp32 buffer's HBM pressure costs the scheduled
    step more than the kernel gap saves. It remains the better backward
    for zero-padded head dims (d96: 6.7ms vs 13.8ms per-kernel, the pad
    taxes the Pallas bwd twice) and is selectable via
    FLAGS_flash_attention_bwd=xla."""
    def f(q_, k_, v_):
        if q_per_kv == 1:
            return _xla_attention_bhsd(q_, k_, v_, causal, scale)
        bh, sq, d = q_.shape
        bkv = k_.shape[0]
        qg = q_.reshape(bkv, q_per_kv, sq, d)
        s = jnp.einsum("bgqd,bkd->bgqk", qg, k_,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            sk = k_.shape[1]
            mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
            s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1).astype(v_.dtype)
        o = jnp.einsum("bgqk,bkd->bgqd", p, v_)
        return o.reshape(bh, sq, d)

    _, pull = jax.vjp(f, q, k, v)
    return pull(g)


_flags.define_flag(
    "flash_attention_bwd", "auto",
    "flash-attention backward: 'pallas' (FA-2 dQ/dKV kernels), 'xla' "
    "(dense rematerialization, XLA-differentiated), or 'auto' (routed "
    "per shape by ops/pallas/attention_router from the baked hardware "
    "ledger: the r5 end-to-end A/B on v5e measured the full-pallas bwd "
    "at 0.426 MFU vs 0.406 for the xla-remat hybrid on the 535m train "
    "step even though isolated-kernel timing favors the hybrid — the "
    "dense remat's O(S^2) buffer costs more in HBM pressure than it "
    "saves in kernel time once the whole step is scheduled — while the "
    "zero-padded d96 shapes measured the hybrid winning both ways)")


def _fa_bwd(causal, scale, q_per_kv, res, g):
    q, k, v, o, lse = res
    mode = _flags.flag_value("flash_attention_bwd")
    if mode == "auto":
        # per-shape routed choice with provenance (ledger -> measurement
        # -> heuristic); 'pallas' if the router itself fails
        try:
            from .attention_router import route
            mode = route(q.shape[0], q.shape[1], k.shape[1], q.shape[2],
                         q.dtype, causal).bwd
        except Exception:
            mode = "pallas"
    if mode == "xla":
        return _dense_remat_bwd(q, k, v, causal, scale, q_per_kv, g)
    bq, bk = _bwd_blocks(q, k, causal)
    return _flash_bwd_bhsd(q, k, v, o, lse, g, causal, scale,
                           block_q=bq, block_k=bk, q_per_kv=q_per_kv)


_flash_attention_bhsd.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """Paddle flash_attention layout: (batch, seq, heads, head_dim).

    GQA-native: k/v may carry FEWER heads than q (num_kv_heads divides
    num_heads); the kernel groups query heads onto shared KV blocks via
    the BlockSpec index map, so the (B, S, H, D) KV broadcast the
    reference materializes never exists, and dK/dV come back reduced."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kvh = k.shape[2]
    if h % kvh:
        raise ValueError(f"num_heads {h} not divisible by kv heads {kvh}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # lane-align the head dim (e.g. 96 -> 128, the llama_780m shape): zero
    # pad columns change neither QK^T nor PV, their grads come back zero,
    # and `scale` is already fixed from the TRUE d above. Costs d_pad/d
    # extra MXU work — cheaper than losing the O(S^2) HBM win at long seq.
    d_pad = (-d) % _LANES
    if d_pad:
        padw = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        q = jnp.pad(q, padw)
        k = jnp.pad(k, padw)
        v = jnp.pad(v, padw)
    dp = d + d_pad
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, dp)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * kvh, sk, dp)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * kvh, sk, dp)
    out = _flash_attention_bhsd(qt, kt, vt, causal, scale, h // kvh)
    out = jnp.swapaxes(out.reshape(b, h, sq, dp), 1, 2)
    return out[..., :d] if d_pad else out


def flash_attention_rms_epilogue_bshd(q, k, v, residual, rms_weight,
                                      causal=True, scale=None, eps=1e-6):
    """Flash attention with the rmsnorm(attn + residual) * gamma epilogue
    FUSED into the kernel's flush step — the attention output is written
    to HBM exactly once, already normalized (the FlashFuser-style
    widened fusion the backend router can select where it wins).

    Layout matches flash_attention_bshd: q (b, sq, h, d), k/v GQA-native
    (b, sk, kvh, d); residual (b, sq, h, d); rms_weight (d,). The norm
    axis is the HEAD dim (per-head RMSNorm — use h=1 for a full-hidden
    norm). Forward-only: no VJP is defined (the training path routes
    through the unfused custom-vjp kernels); intended for inference /
    serving prefill.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kvh = k.shape[2]
    if h % kvh:
        raise ValueError(f"num_heads {h} not divisible by kv heads {kvh}")
    if residual.shape != q.shape:
        raise ValueError(f"residual shape {residual.shape} != q {q.shape}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    d_pad = (-d) % _LANES
    if d_pad:
        padw = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        q = jnp.pad(q, padw)
        k = jnp.pad(k, padw)
        v = jnp.pad(v, padw)
        residual = jnp.pad(residual, padw)
        rms_weight = jnp.pad(rms_weight, ((0, d_pad),))
    dp = d + d_pad
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, dp)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * kvh, sk, dp)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * kvh, sk, dp)
    rt = jnp.swapaxes(residual, 1, 2).reshape(b * h, sq, dp)
    bq, bk = _fwd_blocks(qt, kt, causal)
    out, _ = _flash_fwd_bhsd(qt, kt, vt, causal, scale, block_q=bq,
                             block_k=bk, q_per_kv=h // kvh, residual=rt,
                             rms_weight=rms_weight, rms_eps=eps, rms_d=d)
    out = jnp.swapaxes(out.reshape(b, h, sq, dp), 1, 2)
    return out[..., :d] if d_pad else out
