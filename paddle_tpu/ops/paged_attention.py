"""Paged KV-cache attention (block attention) for inference serving.

reference: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
+ python surface incubate/nn/functional/block_multihead_attention.py —
vLLM-style paged KV cache: the cache is a pool of fixed-size blocks; each
sequence owns a list of block ids (block_tables), so memory is allocated in
block_size granules with no per-sequence max-length reservation.

TPU-native: gathers over the block pool are XLA dynamic-gathers that Mosaic
handles well at decode shapes; the full attention runs as one batched einsum
over the gathered pages (decode q length is 1, so the MXU work is a skinny
matmul — bandwidth-bound, which the gather layout serves).

Cache layout: [num_blocks, block_size, num_kv_heads, head_dim].

Quantized block format (round 11): the pool may store blocks as int8 or
fp8 instead of the native compute dtype. Scales live ALONGSIDE the
blocks in a parallel [num_blocks, block_size, num_kv_heads] array — one
scale per cached (token, head), bfloat16 — so a block and its scales
are gathered by the same table lookup and dequantization fuses into the
attention read (no separate dequant pass, no bf16 copy of the pool ever
materializes in HBM). int8 uses the same symmetric [-qmax, qmax] grid
as nn/quant/format.py; fp8 rounds through the real ml_dtypes storage
types with the same absmax->fmax scaling as fake_fp8_quant, so KV
blocks reproduce exactly what serialized fp8 tensors would.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention_decode", "paged_attention_decode_inner",
           "paged_attention_prefill_chunk", "paged_attention_verify",
           "write_to_cache", "write_chunk_to_cache", "KVBlockFormat",
           "kv_write_token", "kv_write_chunk", "kv_write_tokens",
           "kv_rollback_tokens", "BlockKVCacheManager"]


class KVBlockFormat:
    """Storage format of the paged KV pool: how K/V bytes sit in HBM.

    name:
      "native"/"bf16" -> passthrough: blocks hold `native_dtype`, no
                         scales (the pre-round-11 pool, byte-identical).
      "int8"          -> symmetric absmax int8 per (token, head):
                         q = round(x / s), s = absmax/127 — the same
                         [-qmax, qmax] grid nn/quant/format.py emits.
      "fp8_e4m3"/"fp8_e5m2" -> real ml_dtypes float8 storage (framework/
                         dtypes.py registry), absmax scaled onto the fp8
                         grid exactly like fake_fp8_quant: q = x/s*fmax
                         rounded through the fp8 dtype, x' = q/fmax*s.

    Scales are bfloat16, one per (token, head) — 2 bytes next to D
    payload bytes, so int8 halves the pool's bytes/token at head_dim 64+
    (the ">=1.9x lanes" capacity contract is test-pinned). Encode uses
    the ROUNDED stored scale so decode is its exact inverse modulo the
    payload grid.
    """

    NAMES = ("native", "bf16", "int8", "fp8_e4m3", "fp8_e5m2")

    def __init__(self, name="native", native_dtype=jnp.bfloat16):
        if name not in self.NAMES:
            raise ValueError(
                f"unknown kv cache format {name!r}; one of {self.NAMES}")
        self.name = name
        self.native_dtype = native_dtype
        self.scale_dtype = jnp.bfloat16
        self.quantized = name not in ("native", "bf16")
        if name == "int8":
            self.store_dtype = jnp.int8
            self._qmax = 127.0          # symmetric grid (format.py contract)
            self._fmax = None
        elif self.quantized:
            # fp8: grid limits + storage dtype from THE shared registries
            from ..nn.quant.format import fp8_limits
            from ..framework import dtypes as _dtypes
            fmax, dtype_name = fp8_limits(name.split("_", 1)[1])
            self.store_dtype = _dtypes.NAME2DTYPE[dtype_name]
            self._qmax = None
            self._fmax = fmax
        else:
            self.store_dtype = native_dtype
            self._qmax = self._fmax = None

    def encode(self, x):
        """x [..., D] native -> (payload [..., D] store_dtype,
        scale [...] scale_dtype). Passthrough formats return (x, None)."""
        if not self.quantized:
            return x, None
        x32 = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(x32), axis=-1)
        if self._qmax is not None:                       # int8
            scale = (amax / self._qmax).astype(self.scale_dtype)
            safe = jnp.where(scale > 0, scale, 1.0).astype(jnp.float32)
            q = jnp.clip(jnp.round(x32 / safe[..., None]),
                         -self._qmax, self._qmax).astype(self.store_dtype)
        else:                                            # fp8
            scale = amax.astype(self.scale_dtype)
            safe = jnp.where(scale > 0, scale, 1.0).astype(jnp.float32)
            q = jnp.clip(x32 * self._fmax / safe[..., None],
                         -self._fmax, self._fmax).astype(self.store_dtype)
        return q, scale

    def decode(self, q, scale):
        """Inverse of encode, in the native compute dtype."""
        if not self.quantized:
            return q
        q32 = q.astype(jnp.float32)
        s32 = scale.astype(jnp.float32)[..., None]
        if self._qmax is not None:
            return (q32 * s32).astype(self.native_dtype)
        return (q32 / self._fmax * s32).astype(self.native_dtype)

    def bytes_per_token(self, kv_heads, head_dim):
        """HBM bytes one cached token costs in ONE of the k/v arrays
        (payload + its scales); double for k and v."""
        payload = kv_heads * head_dim * jnp.dtype(self.store_dtype).itemsize
        if not self.quantized:
            return payload
        return payload + kv_heads * jnp.dtype(self.scale_dtype).itemsize


def write_to_cache(k_cache, v_cache, k_new, v_new, block_tables, write_pos,
                   active=None, scratch_block=None):
    """Scatter new K/V (one token per sequence) into the paged cache.

    k_new/v_new: [B, KVH, D]; block_tables: [B, max_blocks] int32;
    write_pos: [B] absolute position of the new token per sequence.
    When `active` ([B] bool) is given, inactive rows write to
    `scratch_block` instead of their table entry — the fused K-step
    decode keeps dead lanes scribbling somewhere no live sequence owns
    without data-dependent control flow. Returns (k_cache, v_cache).
    """
    # kv.write scope: marks the pool scatters as stateful for the PIR
    # verifier's effect-order rule (COMPILER.md "Verifier & dataflow
    # analysis") — a pass may drop a dead write, never reorder live ones
    with jax.named_scope("kv.write"):
        block_size = k_cache.shape[1]
        block_idx = write_pos // block_size                   # [B]
        in_block = write_pos % block_size                     # [B]
        block_ids = jnp.take_along_axis(block_tables, block_idx[:, None],
                                        axis=1)[:, 0]         # [B]
        if active is not None:
            block_ids = jnp.where(active, block_ids, scratch_block)
        k_cache = k_cache.at[block_ids, in_block].set(k_new)
        v_cache = v_cache.at[block_ids, in_block].set(v_new)
        return k_cache, v_cache


def write_chunk_to_cache(k_cache, v_cache, k_new, v_new, table_row, start):
    """Scatter a prompt CHUNK's K/V (one sequence, C contiguous tokens)
    into the paged cache.

    k_new/v_new: [C, KVH, D]; table_row: [max_blocks] int32 block table of
    the owning sequence; start: absolute position of the chunk's first
    token. Positions past the row's allocated entries land in whatever
    the row is padded with (the engine pads with its scratch block).
    """
    with jax.named_scope("kv.write"):
        block_size = k_cache.shape[1]
        pos = start + jnp.arange(k_new.shape[0])
        block_ids = jnp.take(table_row, pos // block_size)
        in_block = pos % block_size
        k_cache = k_cache.at[block_ids, in_block].set(k_new)
        v_cache = v_cache.at[block_ids, in_block].set(v_new)
        return k_cache, v_cache


def _token_slots(block_tables, start_pos, count, block_size,
                 active=None, scratch_block=None):
    """(block_ids [B, C], in_block [B, C]) for `count` contiguous tokens
    per lane starting at start_pos[b]. Dead lanes are routed whole to
    `scratch_block`; positions past a lane's table row clamp to the
    row's last entry (the engine pads rows with its scratch block, so
    overshoot lands in scratch — same contract as write_chunk_to_cache)."""
    pos = start_pos[:, None] + jnp.arange(count)[None, :]      # [B, C]
    block_idx = jnp.clip(pos // block_size, 0, block_tables.shape[1] - 1)
    block_ids = jnp.take_along_axis(block_tables, block_idx, axis=1)
    if active is not None:
        block_ids = jnp.where(active[:, None], block_ids, scratch_block)
    return block_ids, pos % block_size


def kv_write_tokens(fmt, k_cache, v_cache, k_scale, v_scale,
                    k_new, v_new, block_tables, start_pos,
                    active=None, scratch_block=None):
    """Write C contiguous tokens PER LANE (the speculative verify write:
    k_new/v_new [B, C, KVH, D] at positions start_pos[b]..start_pos[b]+C-1),
    saving the pre-write contents of every touched slot for rollback.

    Returns (k_cache, v_cache, k_scale, v_scale, saved) where `saved` is
    a tuple of the old payloads (and old scales when `fmt` quantizes)
    shaped like the writes — feed it to kv_rollback_tokens to restore
    rejected draft positions byte-exactly. Scale caches are [NB, BS, KVH]
    (None for passthrough formats, passed through unchanged).
    """
    with jax.named_scope("kv.write"):
        block_size = k_cache.shape[1]
        bids, inb = _token_slots(block_tables, start_pos, k_new.shape[1],
                                 block_size, active, scratch_block)
        saved_k = k_cache[bids, inb]                           # [B, C, KVH, D]
        saved_v = v_cache[bids, inb]
        if fmt is not None and fmt.quantized:
            qk, sk = fmt.encode(k_new)
            qv, sv = fmt.encode(v_new)
            saved = (saved_k, saved_v, k_scale[bids, inb], v_scale[bids, inb])
            k_scale = k_scale.at[bids, inb].set(sk)
            v_scale = v_scale.at[bids, inb].set(sv)
        else:
            qk, qv = k_new, v_new
            saved = (saved_k, saved_v)
        k_cache = k_cache.at[bids, inb].set(qk.astype(k_cache.dtype))
        v_cache = v_cache.at[bids, inb].set(qv.astype(v_cache.dtype))
        return k_cache, v_cache, k_scale, v_scale, saved


def kv_rollback_tokens(fmt, k_cache, v_cache, k_scale, v_scale, saved,
                       block_tables, start_pos, keep,
                       active=None, scratch_block=None):
    """Restore the slots a kv_write_tokens call touched wherever
    keep[b, i] is False (rejected draft positions). Kept slots' restores
    are redirected to `scratch_block` instead of being masked out — the
    scatter stays dense and branch-free, and scratch contents are
    garbage by contract. Returns (k_cache, v_cache, k_scale, v_scale)."""
    # kv.rollback scope: same effect-order contract as kv.write — a
    # rollback must never migrate past the write it undoes
    with jax.named_scope("kv.rollback"):
        block_size = k_cache.shape[1]
        bids, inb = _token_slots(block_tables, start_pos, keep.shape[1],
                                 block_size, active, scratch_block)
        bids = jnp.where(keep, scratch_block, bids)
        if fmt is not None and fmt.quantized:
            saved_k, saved_v, saved_ks, saved_vs = saved
            k_scale = k_scale.at[bids, inb].set(saved_ks)
            v_scale = v_scale.at[bids, inb].set(saved_vs)
        else:
            saved_k, saved_v = saved
        k_cache = k_cache.at[bids, inb].set(saved_k)
        v_cache = v_cache.at[bids, inb].set(saved_v)
        return k_cache, v_cache, k_scale, v_scale


def kv_write_token(fmt, k_cache, v_cache, k_scale, v_scale, k_new, v_new,
                   block_tables, write_pos, active=None, scratch_block=None):
    """Format-aware single-token write (the non-speculative decode step).
    With a passthrough format this IS write_to_cache — same ops, same
    trace — so the bf16 pool keeps its pre-round-11 bytes. Returns
    (k_cache, v_cache, k_scale, v_scale)."""
    if fmt is None or not fmt.quantized:
        k_cache, v_cache = write_to_cache(k_cache, v_cache, k_new, v_new,
                                          block_tables, write_pos,
                                          active, scratch_block)
        return k_cache, v_cache, k_scale, v_scale
    qk, sk = fmt.encode(k_new)
    qv, sv = fmt.encode(v_new)
    k_cache, v_cache = write_to_cache(k_cache, v_cache, qk, qv,
                                      block_tables, write_pos,
                                      active, scratch_block)
    with jax.named_scope("kv.write"):
        bids, inb = _token_slots(block_tables, write_pos, 1,
                                 k_cache.shape[1], active, scratch_block)
        k_scale = k_scale.at[bids[:, 0], inb[:, 0]].set(sk)
        v_scale = v_scale.at[bids[:, 0], inb[:, 0]].set(sv)
    return k_cache, v_cache, k_scale, v_scale


def kv_write_chunk(fmt, k_cache, v_cache, k_scale, v_scale, k_new, v_new,
                   table_row, start):
    """Format-aware write_chunk_to_cache (one sequence, C contiguous
    prompt tokens [C, KVH, D]). Passthrough formats take the original
    code path untouched. Returns (k_cache, v_cache, k_scale, v_scale)."""
    if fmt is None or not fmt.quantized:
        k_cache, v_cache = write_chunk_to_cache(k_cache, v_cache, k_new,
                                                v_new, table_row, start)
        return k_cache, v_cache, k_scale, v_scale
    qk, sk = fmt.encode(k_new)
    qv, sv = fmt.encode(v_new)
    k_cache, v_cache = write_chunk_to_cache(k_cache, v_cache, qk, qv,
                                            table_row, start)
    with jax.named_scope("kv.write"):
        block_size = k_cache.shape[1]
        pos = start + jnp.arange(k_new.shape[0])
        block_ids = jnp.take(table_row, pos // block_size)
        in_block = pos % block_size
        k_scale = k_scale.at[block_ids, in_block].set(sk)
        v_scale = v_scale.at[block_ids, in_block].set(sv)
    return k_cache, v_cache, k_scale, v_scale


def paged_attention_decode_inner(q, k_cache, v_cache, block_tables,
                                 seq_lens, scale=None, fmt=None,
                                 k_scale_cache=None, v_scale_cache=None):
    """Unjitted body of paged_attention_decode — call this from inside an
    already-compiled program (e.g. the serving engine's fused K-step
    decode scan) so XLA sees one flat program instead of a nested pjit
    call per layer per step.

    With a quantized `fmt`, blocks are gathered in their storage dtype
    and dequantized against the per-(token, head) scale caches right at
    the read — XLA fuses the dequant into the gather, so no bf16 copy of
    the pool materializes. fmt=None keeps the original trace."""
    B, H, D = q.shape
    _, block_size, KVH, _ = k_cache.shape
    groups = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    max_blocks = block_tables.shape[1]
    L = max_blocks * block_size
    dequant = fmt is not None and fmt.quantized

    def one(qb, table, n):
        k = k_cache[table]                                    # [mb, bs, KVH, D]
        v = v_cache[table]
        if dequant:
            k = fmt.decode(k, k_scale_cache[table])
            v = fmt.decode(v, v_scale_cache[table])
        k = k.reshape(L, KVH, D)
        v = v.reshape(L, KVH, D)
        qg = qb.reshape(KVH, groups, D)
        # scores[kvh, g, l]
        s = jnp.einsum("hgd,lhd->hgl", qg, k) * scale
        mask = jnp.arange(L) < n
        s = jnp.where(mask[None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hgl,lhd->hgd", p, v)
        return o.reshape(H, D)

    return jax.vmap(one)(q, block_tables, seq_lens)


@functools.partial(jax.jit, static_argnames=("scale",))
def paged_attention_decode(q, k_cache, v_cache, block_tables, seq_lens,
                           scale=None):
    """One decode step over paged caches.

    q: [B, H, D] (single new token per sequence);
    k_cache/v_cache: [num_blocks, block_size, KVH, D];
    block_tables: [B, max_blocks_per_seq]; seq_lens: [B] (incl. new token).
    Supports GQA (H a multiple of KVH). Returns [B, H, D].
    """
    return paged_attention_decode_inner(q, k_cache, v_cache, block_tables,
                                        seq_lens, scale=scale)


def paged_attention_verify(q, k_cache, v_cache, block_tables, base_lens,
                           scale=None, fmt=None, k_scale_cache=None,
                           v_scale_cache=None):
    """Speculative-verify attention: C queries PER LANE (the step token
    plus D draft tokens, already written to the pool) attend causally
    over each lane's cache.

    q: [B, C, H, D]; base_lens: [B] — the lane length BEFORE this step's
    write, so query i sits at absolute position base_lens[b] + i and
    attends to every cached position `p <= base_lens[b] + i`. This is
    write_chunk/prefill-chunk masking batched over lanes; with C == 1 it
    computes exactly what paged_attention_decode_inner computes for
    seq_lens = base_lens + 1. Returns [B, C, H, D].
    """
    B, C, H, D = q.shape
    _, block_size, KVH, _ = k_cache.shape
    groups = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    L = block_tables.shape[1] * block_size
    dequant = fmt is not None and fmt.quantized

    def one(qb, table, n0):
        k = k_cache[table]
        v = v_cache[table]
        if dequant:
            k = fmt.decode(k, k_scale_cache[table])
            v = fmt.decode(v, v_scale_cache[table])
        k = k.reshape(L, KVH, D)
        v = v.reshape(L, KVH, D)
        qg = qb.reshape(C, KVH, groups, D)
        s = jnp.einsum("chgd,lhd->chgl", qg, k,
                       preferred_element_type=jnp.float32) * scale
        pos_q = n0 + jnp.arange(C)
        valid = jnp.arange(L)[None, :] <= pos_q[:, None]       # [C, L]
        s = jnp.where(valid[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("chgl,lhd->chgd", p, v)
        return o.reshape(C, H, D)

    return jax.vmap(one)(q, block_tables, base_lens)


def paged_attention_prefill_chunk(q, k_cache, v_cache, table_row, start,
                                  scale=None, fmt=None, k_scale_cache=None,
                                  v_scale_cache=None):
    """Chunked-prefill attention for ONE sequence: C chunk queries attend
    over every cached position `p <= start + qi` — earlier chunks already
    scattered into the paged pool plus the (just-written) chunk itself,
    causal within the chunk.

    q: [C, H, D] (rotated chunk queries); k_cache/v_cache:
    [num_blocks, block_size, KVH, D] AFTER write_chunk_to_cache for this
    chunk; table_row: [max_blocks] int32; start: absolute position of the
    chunk's first token. Returns [C, H, D].
    """
    C, H, D = q.shape
    _, block_size, KVH, _ = k_cache.shape
    groups = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    L = table_row.shape[0] * block_size
    k = k_cache[table_row]
    v = v_cache[table_row]
    if fmt is not None and fmt.quantized:
        k = fmt.decode(k, k_scale_cache[table_row])
        v = fmt.decode(v, v_scale_cache[table_row])
    k = k.reshape(L, KVH, D)
    v = v.reshape(L, KVH, D)
    qg = q.reshape(C, KVH, groups, D)
    s = jnp.einsum("chgd,lhd->chgl", qg, k,
                   preferred_element_type=jnp.float32) * scale
    pos_q = start + jnp.arange(C)
    valid = jnp.arange(L)[None, :] <= pos_q[:, None]          # [C, L]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("chgl,lhd->chgd", p, v)
    return o.reshape(C, H, D)


class BlockKVCacheManager:
    """Host-side block allocator — the analog of the reference's block table
    management in block_multihead_attention (paged KV serving loop).

    Round 18: blocks are refcounted so sequences can SHARE a prompt
    prefix (`share`), with copy-on-write (`fork_cow`) before any write
    into a shared block. `free` decrements; a block returns to the free
    list only when its last holder lets go. Sequences that never share
    behave exactly as before."""

    def __init__(self, num_blocks, block_size, num_kv_heads, head_dim,
                 dtype=jnp.bfloat16):
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.k_cache = jnp.zeros((num_blocks, block_size, num_kv_heads,
                                  head_dim), dtype)
        self.v_cache = jnp.zeros_like(self.k_cache)
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables = {}   # seq_id -> [block ids]
        self._lens = {}     # seq_id -> length
        self._ref = {}      # block id -> refcount (absent == free)

    def allocate(self, seq_id, num_tokens):
        """Ensure capacity for `num_tokens` total tokens."""
        need = (num_tokens + self.block_size - 1) // self.block_size
        table = self._tables.setdefault(seq_id, [])
        while len(table) < need:
            if not self._free:
                raise MemoryError("KV cache pool exhausted")
            b = self._free.pop()
            self._ref[b] = 1
            table.append(b)
        self._lens[seq_id] = num_tokens
        return table

    def free(self, seq_id):
        for b in self._tables.pop(seq_id, []):
            n = self._ref.get(b, 1) - 1
            if n <= 0:
                self._ref.pop(b, None)
                self._free.append(b)
            else:
                self._ref[b] = n
        self._lens.pop(seq_id, None)

    def share(self, src_id, dst_id, num_blocks):
        """Start dst's table with src's first `num_blocks` blocks
        (refcount +1 each): a prompt-prefix hit. dst must be fresh; its
        tail grows through the usual allocate()."""
        if self._tables.get(dst_id):
            raise ValueError(f"share into non-empty sequence {dst_id!r}")
        src = self._tables[src_id][:num_blocks]
        table = self._tables.setdefault(dst_id, [])
        for b in src:
            self._ref[b] = self._ref.get(b, 0) + 1
            table.append(b)
        self._lens[dst_id] = len(table) * self.block_size
        return table

    def fork_cow(self, seq_id, idx):
        """Give seq_id a private copy of its idx-th block before a write
        lands in it (no-op when already private). Byte-exact device
        copy; the old block loses one reference."""
        old = self._tables[seq_id][idx]
        if self._ref.get(old, 1) <= 1:
            return old
        if not self._free:
            raise MemoryError("KV cache pool exhausted (COW fork)")
        new = self._free.pop()
        self._ref[new] = 1
        self.k_cache = self.k_cache.at[new].set(self.k_cache[old])
        self.v_cache = self.v_cache.at[new].set(self.v_cache[old])
        self._tables[seq_id][idx] = new
        n = self._ref.get(old, 1) - 1
        if n <= 0:
            self._ref.pop(old, None)
            self._free.append(old)
        else:
            self._ref[old] = n
        return new

    def prefill(self, seq_id, k, v):
        """Write a whole prompt's K/V ([L, KVH, D]) into fresh blocks."""
        L = k.shape[0]
        table = self.allocate(seq_id, L)
        bs = self.block_size
        pad = (len(table) * bs) - L
        kp = jnp.pad(k, ((0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, pad), (0, 0), (0, 0)))
        ids = jnp.asarray(table)
        self.k_cache = self.k_cache.at[ids].set(
            kp.reshape(len(table), bs, *k.shape[1:]))
        self.v_cache = self.v_cache.at[ids].set(
            vp.reshape(len(table), bs, *v.shape[1:]))
        return table

    def append(self, seq_id, k_new, v_new):
        """Append one token's K/V ([KVH, D]); returns new length."""
        n = self._lens[seq_id]
        table = self.allocate(seq_id, n + 1)
        pos = jnp.asarray([n])
        tbl = jnp.asarray([table])
        self.k_cache, self.v_cache = write_to_cache(
            self.k_cache, self.v_cache, k_new[None], v_new[None],
            tbl, pos)
        return n + 1

    def batch_tables(self, seq_ids, pad_to=None):
        """Dense [B, max_blocks] table + [B] lengths for a decode batch."""
        import numpy as np
        mb = max(len(self._tables[s]) for s in seq_ids)
        if pad_to:
            mb = max(mb, pad_to)
        tables = np.zeros((len(seq_ids), mb), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self._tables[s]
            tables[i, :len(t)] = t
            lens[i] = self._lens[s]
        return jnp.asarray(tables), jnp.asarray(lens)
