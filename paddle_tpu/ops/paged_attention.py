"""Paged KV-cache attention (block attention) for inference serving.

reference: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
+ python surface incubate/nn/functional/block_multihead_attention.py —
vLLM-style paged KV cache: the cache is a pool of fixed-size blocks; each
sequence owns a list of block ids (block_tables), so memory is allocated in
block_size granules with no per-sequence max-length reservation.

TPU-native: gathers over the block pool are XLA dynamic-gathers that Mosaic
handles well at decode shapes; the full attention runs as one batched einsum
over the gathered pages (decode q length is 1, so the MXU work is a skinny
matmul — bandwidth-bound, which the gather layout serves).

Cache layout: [num_blocks, block_size, num_kv_heads, head_dim].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention_decode", "paged_attention_decode_inner",
           "paged_attention_prefill_chunk", "write_to_cache",
           "write_chunk_to_cache", "BlockKVCacheManager"]


def write_to_cache(k_cache, v_cache, k_new, v_new, block_tables, write_pos,
                   active=None, scratch_block=None):
    """Scatter new K/V (one token per sequence) into the paged cache.

    k_new/v_new: [B, KVH, D]; block_tables: [B, max_blocks] int32;
    write_pos: [B] absolute position of the new token per sequence.
    When `active` ([B] bool) is given, inactive rows write to
    `scratch_block` instead of their table entry — the fused K-step
    decode keeps dead lanes scribbling somewhere no live sequence owns
    without data-dependent control flow. Returns (k_cache, v_cache).
    """
    block_size = k_cache.shape[1]
    block_idx = write_pos // block_size                       # [B]
    in_block = write_pos % block_size                         # [B]
    block_ids = jnp.take_along_axis(block_tables, block_idx[:, None],
                                    axis=1)[:, 0]             # [B]
    if active is not None:
        block_ids = jnp.where(active, block_ids, scratch_block)
    k_cache = k_cache.at[block_ids, in_block].set(k_new)
    v_cache = v_cache.at[block_ids, in_block].set(v_new)
    return k_cache, v_cache


def write_chunk_to_cache(k_cache, v_cache, k_new, v_new, table_row, start):
    """Scatter a prompt CHUNK's K/V (one sequence, C contiguous tokens)
    into the paged cache.

    k_new/v_new: [C, KVH, D]; table_row: [max_blocks] int32 block table of
    the owning sequence; start: absolute position of the chunk's first
    token. Positions past the row's allocated entries land in whatever
    the row is padded with (the engine pads with its scratch block).
    """
    block_size = k_cache.shape[1]
    pos = start + jnp.arange(k_new.shape[0])
    block_ids = jnp.take(table_row, pos // block_size)
    in_block = pos % block_size
    k_cache = k_cache.at[block_ids, in_block].set(k_new)
    v_cache = v_cache.at[block_ids, in_block].set(v_new)
    return k_cache, v_cache


def paged_attention_decode_inner(q, k_cache, v_cache, block_tables,
                                 seq_lens, scale=None):
    """Unjitted body of paged_attention_decode — call this from inside an
    already-compiled program (e.g. the serving engine's fused K-step
    decode scan) so XLA sees one flat program instead of a nested pjit
    call per layer per step."""
    B, H, D = q.shape
    _, block_size, KVH, _ = k_cache.shape
    groups = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    max_blocks = block_tables.shape[1]
    L = max_blocks * block_size

    def one(qb, table, n):
        k = k_cache[table]                                    # [mb, bs, KVH, D]
        v = v_cache[table]
        k = k.reshape(L, KVH, D)
        v = v.reshape(L, KVH, D)
        qg = qb.reshape(KVH, groups, D)
        # scores[kvh, g, l]
        s = jnp.einsum("hgd,lhd->hgl", qg, k) * scale
        mask = jnp.arange(L) < n
        s = jnp.where(mask[None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hgl,lhd->hgd", p, v)
        return o.reshape(H, D)

    return jax.vmap(one)(q, block_tables, seq_lens)


@functools.partial(jax.jit, static_argnames=("scale",))
def paged_attention_decode(q, k_cache, v_cache, block_tables, seq_lens,
                           scale=None):
    """One decode step over paged caches.

    q: [B, H, D] (single new token per sequence);
    k_cache/v_cache: [num_blocks, block_size, KVH, D];
    block_tables: [B, max_blocks_per_seq]; seq_lens: [B] (incl. new token).
    Supports GQA (H a multiple of KVH). Returns [B, H, D].
    """
    return paged_attention_decode_inner(q, k_cache, v_cache, block_tables,
                                        seq_lens, scale=scale)


def paged_attention_prefill_chunk(q, k_cache, v_cache, table_row, start,
                                  scale=None):
    """Chunked-prefill attention for ONE sequence: C chunk queries attend
    over every cached position `p <= start + qi` — earlier chunks already
    scattered into the paged pool plus the (just-written) chunk itself,
    causal within the chunk.

    q: [C, H, D] (rotated chunk queries); k_cache/v_cache:
    [num_blocks, block_size, KVH, D] AFTER write_chunk_to_cache for this
    chunk; table_row: [max_blocks] int32; start: absolute position of the
    chunk's first token. Returns [C, H, D].
    """
    C, H, D = q.shape
    _, block_size, KVH, _ = k_cache.shape
    groups = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    L = table_row.shape[0] * block_size
    k = k_cache[table_row].reshape(L, KVH, D)
    v = v_cache[table_row].reshape(L, KVH, D)
    qg = q.reshape(C, KVH, groups, D)
    s = jnp.einsum("chgd,lhd->chgl", qg, k,
                   preferred_element_type=jnp.float32) * scale
    pos_q = start + jnp.arange(C)
    valid = jnp.arange(L)[None, :] <= pos_q[:, None]          # [C, L]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("chgl,lhd->chgd", p, v)
    return o.reshape(C, H, D)


class BlockKVCacheManager:
    """Host-side block allocator — the analog of the reference's block table
    management in block_multihead_attention (paged KV serving loop)."""

    def __init__(self, num_blocks, block_size, num_kv_heads, head_dim,
                 dtype=jnp.bfloat16):
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.k_cache = jnp.zeros((num_blocks, block_size, num_kv_heads,
                                  head_dim), dtype)
        self.v_cache = jnp.zeros_like(self.k_cache)
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables = {}   # seq_id -> [block ids]
        self._lens = {}     # seq_id -> length

    def allocate(self, seq_id, num_tokens):
        """Ensure capacity for `num_tokens` total tokens."""
        need = (num_tokens + self.block_size - 1) // self.block_size
        table = self._tables.setdefault(seq_id, [])
        while len(table) < need:
            if not self._free:
                raise MemoryError("KV cache pool exhausted")
            table.append(self._free.pop())
        self._lens[seq_id] = num_tokens
        return table

    def free(self, seq_id):
        for b in self._tables.pop(seq_id, []):
            self._free.append(b)
        self._lens.pop(seq_id, None)

    def prefill(self, seq_id, k, v):
        """Write a whole prompt's K/V ([L, KVH, D]) into fresh blocks."""
        L = k.shape[0]
        table = self.allocate(seq_id, L)
        bs = self.block_size
        pad = (len(table) * bs) - L
        kp = jnp.pad(k, ((0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, pad), (0, 0), (0, 0)))
        ids = jnp.asarray(table)
        self.k_cache = self.k_cache.at[ids].set(
            kp.reshape(len(table), bs, *k.shape[1:]))
        self.v_cache = self.v_cache.at[ids].set(
            vp.reshape(len(table), bs, *v.shape[1:]))
        return table

    def append(self, seq_id, k_new, v_new):
        """Append one token's K/V ([KVH, D]); returns new length."""
        n = self._lens[seq_id]
        table = self.allocate(seq_id, n + 1)
        pos = jnp.asarray([n])
        tbl = jnp.asarray([table])
        self.k_cache, self.v_cache = write_to_cache(
            self.k_cache, self.v_cache, k_new[None], v_new[None],
            tbl, pos)
        return n + 1

    def batch_tables(self, seq_ids, pad_to=None):
        """Dense [B, max_blocks] table + [B] lengths for a decode batch."""
        import numpy as np
        mb = max(len(self._tables[s]) for s in seq_ids)
        if pad_to:
            mb = max(mb, pad_to)
        tables = np.zeros((len(seq_ids), mb), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self._tables[s]
            tables[i, :len(t)] = t
            lens[i] = self._lens[s]
        return jnp.asarray(tables), jnp.asarray(lens)
