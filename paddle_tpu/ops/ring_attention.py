"""Ring attention: exact attention over sequence shards (context parallelism).

reference capability: the SEP/"segment parallel" axis
(python/paddle/distributed/fleet/meta_parallel/segment_parallel.py:26,
fleet/base/topology.py:199). The reference splits sequences across ranks but
ships NO ring-attention kernel (SURVEY.md §5) — attention there requires
gathering the sequence. This module fills that gap TPU-natively:

- K/V shards rotate around the ring with jax.lax.ppermute over the mesh
  axis (ICI neighbor exchange — the optimal topology for a TPU torus).
- Each step computes a partial attention of the local Q block against the
  visiting K/V block; partials merge with the numerically-stable
  log-sum-exp recurrence (same math as flash attention's online softmax).
- Communication overlaps compute: XLA schedules the ppermute DMA of step
  i+1 concurrently with the matmuls of step i.

Use inside shard_map with sequences sharded on `axis_name`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _partial_attention(q, k, v, scale, mask=None):
    """Returns unnormalized (acc, m, l) for merging. q/k/v: (B, S, H, D)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Q,1)
    # guard all-masked rows
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m_safe, l


def _ring_flash(q, k, v, axis_name: str, causal: bool, scale: float):
    """Flash-kernel ring: each visiting K/V block runs through the Pallas
    streaming kernel (no O(S_local^2) score materialization) and partials
    merge by the (out, lse) recurrence. Kernel roles stay STATIC — the
    first block is always this shard's own (causal diagonal), and in the
    scan every block runs the non-causal kernel with skipped blocks killed
    by masking their lse to -inf before the merge (no runtime branch
    around a pallas call)."""
    from .pallas.flash_attention import _flash_fwd_bhsd, _interpret_default
    b, s_local, h, d = q.shape
    interp = _interpret_default()
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, s_local, d)
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    def flash(k_cur, v_cur, block_causal):
        kf = jnp.swapaxes(k_cur, 1, 2).reshape(b * h, s_local, d)
        vf = jnp.swapaxes(v_cur, 1, 2).reshape(b * h, s_local, d)
        if interp:
            # the pallas INTERPRETER can't evaluate under shard_map's
            # varying-manual-axes tracking (dynamic_slice vma mismatch,
            # jax-ml/jax check_vma limitation) — on non-TPU backends run a
            # dense block computation with the kernel's exact (out, lse)
            # contract so the ring merge/masking logic is still tested
            s = jnp.einsum("bqd,bkd->bqk", qf, kf,
                           preferred_element_type=jnp.float32) * scale
            if block_causal:
                rows = jnp.arange(s_local)[:, None]
                s = jnp.where(rows >= jnp.arange(s_local)[None, :], s,
                              NEG_INF)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
            out = jnp.einsum("bqk,bkd->bqd", p / l, vf.astype(jnp.float32))
            return out, (m + jnp.log(l))[..., 0]
        out, lse = _flash_fwd_bhsd(qf, kf, vf, block_causal, scale,
                                   interpret=False)
        return out.astype(jnp.float32), lse[:, :s_local]

    def merge(carry, part):
        out, lse = carry
        out_i, lse_i = part
        lse_new = jnp.logaddexp(lse, lse_i)
        w = jnp.exp(lse - lse_new)[..., None]
        w_i = jnp.exp(lse_i - lse_new)[..., None]
        return out * w + out_i * w_i, lse_new

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # the first visiting block is ALWAYS this shard's own (the causal
    # diagonal) — its kernel role is static, no runtime branch around the
    # pallas call (lax.switch over pallas bodies trips XLA lowering)
    out, lse = flash(k, v, causal)
    k_cur = jax.lax.ppermute(k, axis_name, perm)
    v_cur = jax.lax.ppermute(v, axis_name, perm)

    def step(carry, i):
        ol, k_cur, v_cur = carry
        out_i, lse_i = flash(k_cur, v_cur, False)
        if causal:
            # visiting block index = (my_idx - 1 - i) mod size; under
            # causal attention only blocks strictly BEFORE mine contribute
            # (masking the lse kills skipped blocks in the merge — the
            # kernel role stays static)
            kv_idx = jnp.mod(my_idx - 1 - i, axis_size)
            valid = kv_idx < my_idx
            lse_i = jnp.where(valid, lse_i, NEG_INF)
        ol = merge(ol, (out_i, lse_i))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (ol, k_nxt, v_nxt), None

    ((out, lse), _, _), _ = jax.lax.scan(
        step, ((out, lse), k_cur, v_cur), jnp.arange(axis_size - 1))
    out = out.reshape(b, h, s_local, d)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash_diff(q, k, v, axis_name, causal, scale):
    return _ring_flash(q, k, v, axis_name, causal, scale)


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    return _ring_flash(q, k, v, axis_name, causal, scale), (q, k, v)


def _ring_flash_bwd(axis_name, causal, scale, res, g):
    # pallas_call has no AD rule; the backward recomputes through the dense
    # ring (numerically identical forward) and differentiates that —
    # rematerialization, same contract as flash attention's own bwd split
    q, k, v = res
    _, pull = jax.vjp(
        lambda q_, k_, v_: _ring_dense(q_, k_, v_, axis_name, causal, scale),
        q, k, v)
    return pull(g)


_ring_flash_diff.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: float | None = None, use_flash: bool | None = None):
    """Exact attention where q/k/v are sharded on the sequence dim over
    `axis_name`. Layout: (batch, local_seq, heads, head_dim).

    Must be called inside shard_map/pjit with `axis_name` in scope.
    use_flash: route each visiting block through the Pallas streaming
    kernel (default: on TPU) instead of the dense einsum partial — the
    local block never materializes an S_local x S_local score matrix, so
    per-shard sequence length is HBM-bound, not VMEM/score-bound. The
    flash forward is paired (custom_vjp) with the dense ring as its
    backward, so jax.grad works identically on both paths.
    """
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if use_flash:
        return _ring_flash_diff(q, k, v, axis_name, causal, scale)
    return _ring_dense(q, k, v, axis_name, causal, scale)


def _ring_dense(q, k, v, axis_name, causal, scale):
    b, s_local, h, d = q.shape
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    q_global = my_idx * s_local + jnp.arange(s_local)

    def mask_for(kv_idx):
        if not causal:
            return None
        k_global = kv_idx * s_local + jnp.arange(s_local)
        return (q_global[:, None] >= k_global[None, :])[None, None]  # (1,1,Q,K)

    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local, 1), jnp.float32)
    if hasattr(jax.lax, "pcast"):
        # new-style shard_map tracks varying-manual-axes; mark the carries
        # as varying over the ring axis so the scan carry types match
        acc, m, l = (jax.lax.pcast(x, (axis_name,), to="varying")
                     for x in (acc, m, l))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge(carry, k_cur, v_cur, kv_idx):
        acc, m, l = carry
        acc_i, m_i, l_i = _partial_attention(q, k_cur, v_cur, scale,
                                             mask_for(kv_idx))
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        return (acc * alpha + acc_i * beta, m_new, l * alpha + l_i * beta)

    def step(carry, _):
        acc_m_l, k_cur, v_cur, kv_idx = carry
        acc_m_l = merge(acc_m_l, k_cur, v_cur, kv_idx)
        # rotate k/v to the next ring position (ICI neighbor exchange)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = jnp.asarray((kv_idx - 1) % axis_size, jnp.int32)
        return (acc_m_l, k_nxt, v_nxt, kv_idx), None

    # first axis_size-1 steps rotate; the final block is merged without a
    # wasted trailing ppermute
    ((acc, m, l), k_last, v_last, kv_last), _ = jax.lax.scan(
        step, ((acc, m, l), k, v, jnp.asarray(my_idx, jnp.int32)), None,
        length=axis_size - 1)
    acc, m, l = merge((acc, m, l), k_last, v_last, kv_last)

    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # back to (B, S, H, D)
