"""Ring attention: exact attention over sequence shards (context parallelism).

reference capability: the SEP/"segment parallel" axis
(python/paddle/distributed/fleet/meta_parallel/segment_parallel.py:26,
fleet/base/topology.py:199). The reference splits sequences across ranks but
ships NO ring-attention kernel (SURVEY.md §5) — attention there requires
gathering the sequence. This module fills that gap TPU-natively:

- K/V shards rotate around the ring with jax.lax.ppermute over the mesh
  axis (ICI neighbor exchange — the optimal topology for a TPU torus).
- Each step computes a partial attention of the local Q block against the
  visiting K/V block; partials merge with the numerically-stable
  log-sum-exp recurrence (same math as flash attention's online softmax).
- Communication overlaps compute: XLA schedules the ppermute DMA of step
  i+1 concurrently with the matmuls of step i.

Use inside shard_map with sequences sharded on `axis_name`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _partial_attention(q, k, v, scale, mask=None):
    """Returns unnormalized (acc, m, l) for merging. q/k/v: (B, S, H, D)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Q,1)
    # guard all-masked rows
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m_safe, l


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: float | None = None):
    """Exact attention where q/k/v are sharded on the sequence dim over
    `axis_name`. Layout: (batch, local_seq, heads, head_dim).

    Must be called inside shard_map/pjit with `axis_name` in scope.
    """
    b, s_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    q_global = my_idx * s_local + jnp.arange(s_local)

    def mask_for(kv_idx):
        if not causal:
            return None
        k_global = kv_idx * s_local + jnp.arange(s_local)
        return (q_global[:, None] >= k_global[None, :])[None, None]  # (1,1,Q,K)

    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local, 1), jnp.float32)
    if hasattr(jax.lax, "pcast"):
        # new-style shard_map tracks varying-manual-axes; mark the carries
        # as varying over the ring axis so the scan carry types match
        acc, m, l = (jax.lax.pcast(x, (axis_name,), to="varying")
                     for x in (acc, m, l))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge(carry, k_cur, v_cur, kv_idx):
        acc, m, l = carry
        acc_i, m_i, l_i = _partial_attention(q, k_cur, v_cur, scale,
                                             mask_for(kv_idx))
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        return (acc * alpha + acc_i * beta, m_new, l * alpha + l_i * beta)

    def step(carry, _):
        acc_m_l, k_cur, v_cur, kv_idx = carry
        acc_m_l = merge(acc_m_l, k_cur, v_cur, kv_idx)
        # rotate k/v to the next ring position (ICI neighbor exchange)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = jnp.asarray((kv_idx - 1) % axis_size, jnp.int32)
        return (acc_m_l, k_nxt, v_nxt, kv_idx), None

    # first axis_size-1 steps rotate; the final block is merged without a
    # wasted trailing ppermute
    ((acc, m, l), k_last, v_last, kv_last), _ = jax.lax.scan(
        step, ((acc, m, l), k, v, jnp.asarray(my_idx, jnp.int32)), None,
        length=axis_size - 1)
    acc, m, l = merge((acc, m, l), k_last, v_last, kv_last)

    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # back to (B, S, H, D)
