"""GSPMD-style sharding propagation over pir programs.

reference: GSPMD (arXiv:2105.04663) annotation propagation, and the
SNIPPETS.md ``match_partition_rules`` param-tree idiom. The user
annotates *program inputs* sparsely (regex rules over the param-tree
path, exactly like parallel/spmd.py's rule tables); this pass pushes
``Value.sharding`` forward AND backward through the dataflow until
fixpoint, so a captured llama train step or fused decode comes out
mesh-sharded with no hand annotation inside the program.

Division of labor with the analysis layer (COMPILER.md): the
``ShardingConsistency`` lattice is the *consistency* half of GSPMD —
this pass is the *decision* half. Where operand annotations genuinely
diverge (a contracting dot, a transpose, two user annotations meeting
at an add), the pass either derives the op-specific output sharding or
resolves the conflict by CostModel reshard price, stamps the op with an
``attrs["sharding_rule"]`` contract, and then the consistency analysis
re-runs as proof. Interior annotations the pass did NOT derive are
never resolved away — a forged stamp is left for the verifier's
sharding-conflict rule to reject.

Constraint emission happens at replay: ``Program.bind`` re-asserts
every annotated value through ``jax.lax.with_sharding_constraint``
whenever a mesh scope is active (the pass pins the scope's mesh on the
program so the pipeline's jitted evaluator traces under it). Axes that
are missing from the mesh or do not divide the dimension are dropped —
sharding hints may never change numerics or break a compile.

Fixpoint bound: ``MAX_SWEEPS`` (8) forward+backward sweeps; facts are
monotone (a value is annotated at most once, never overwritten), so
the bound is a guard rail, not a tuning knob.
"""

from __future__ import annotations

import hashlib
import re
from contextlib import contextmanager
from typing import Any, Optional

from .analysis import CONFLICT, CostModel, FlatLattice
from .ir import Operation, Program, Value
from .passes import Pass, PassResult

__all__ = ["ShardingPropagation", "mesh_scope", "current_mesh",
           "current_search", "match_partition_rules", "flat_input_specs",
           "annotate_inputs", "apply_constraint", "propagate_facts",
           "sharding_cache_tag", "MAX_SWEEPS"]

MAX_SWEEPS = 8

# active mesh (jax.sharding.Mesh) + optional search space for the
# cost-driven sharding search (pir/shard_search.py reads it)
_SCOPE: list = [None, None]


@contextmanager
def mesh_scope(mesh, search=None):
    """Activate a mesh for the pipeline: the propagation/search passes
    pick it up, and annotated programs replay their values through
    with_sharding_constraint while (and after) the scope is entered —
    the propagation pass pins the mesh on the program, so the jitted
    evaluator stays sharded once compiled under a scope. ``search``
    optionally carries the strategy space for pir/shard_search.py:
    ``[(name, rules)]`` with rules in match_partition_rules form."""
    prev = list(_SCOPE)
    _SCOPE[0], _SCOPE[1] = mesh, search
    try:
        yield mesh
    finally:
        _SCOPE[0], _SCOPE[1] = prev


def current_mesh():
    return _SCOPE[0]


def current_search():
    return _SCOPE[1]


# --------------------------------------------------------------------------
# user annotation front door (SNIPPETS.md match_partition_rules style)
# --------------------------------------------------------------------------

def _path_name(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(p) if key is None else str(key))
    return "/".join(parts)


def match_partition_rules(rules, tree, *, default="raise"):
    """First rule whose regex ``re.search``-matches the '/'-joined tree
    path wins (the SNIPPETS.md exemplar); scalars replicate to ``()``.
    Returns the flat ``[(name, spec)]`` list in tree_flatten leaf order.
    ``default`` is used for unmatched leaves; the exemplar's behavior
    (raise) is kept as the default."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = _path_name(path)
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0:
            out.append((name, ()))
            continue
        for pat, spec in rules:
            if re.search(pat, name):
                out.append((name, None if spec is None else tuple(spec)))
                break
        else:
            if default == "raise":
                raise ValueError(
                    f"no sharding rule matches param {name!r}")
            out.append((name, default))
    return out


def flat_input_specs(args, rules, *, default=None):
    """Specs for ``tree_flatten(args)`` leaf order — what compile_flat's
    ``input_shardings=`` wants. ``rules`` is a ``[(regex, spec)]`` list
    matched on '/'-joined tree paths; unmatched leaves get ``default``
    (None = unannotated) rather than the exemplar's raise."""
    return [spec for _, spec in
            match_partition_rules(rules, args, default=default)]


def annotate_inputs(prog: Program, specs) -> int:
    """Stamp sanitized sharding specs onto ``prog.inputs`` (None entries
    skip; list may be shorter than the input count). Returns the number
    of inputs annotated."""
    mesh_axes = _mesh_axis_sizes(current_mesh())
    n = 0
    for v, spec in zip(prog.inputs, specs):
        if spec is None:
            continue
        v.sharding = _sanitize(spec, v.shape, mesh_axes)
        n += 1
    return n


def sharding_cache_tag(specs) -> str:
    """Compile-cache key tag for an annotated compile: the input specs
    plus the scope mesh's axis sizes (the traced-in constraints differ
    per mesh, so artifacts must not be shared across them)."""
    mesh = current_mesh()
    axes = sorted(_mesh_axis_sizes(mesh).items()) if mesh else []
    text = repr([None if s is None else tuple(s) for s in specs]) \
        + repr(axes)
    return "spec:" + hashlib.sha256(text.encode()).hexdigest()[:16]


def _mesh_axis_sizes(mesh) -> dict:
    if mesh is None:
        return {}
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:  # noqa: BLE001 — duck-typed test meshes
        return {}


# --------------------------------------------------------------------------
# spec plumbing
# --------------------------------------------------------------------------

def _pad(spec, ndim):
    if spec is None:
        return None
    spec = tuple(spec)[:ndim]
    return spec + (None,) * (ndim - len(spec))


def _sanitize(spec, shape, mesh_axes: Optional[dict] = None):
    """Full-rank spec with duplicate axes dropped and (when the mesh is
    known) axes that are absent or do not divide the dim dropped — the
    same discipline as parallel/spmd.py shard_params_by_rules."""
    if spec is None:
        return None
    spec = _pad(spec, len(shape))
    seen: set = set()
    out = []
    for d, a in enumerate(spec):
        if a is None or a in seen:
            out.append(None)
            continue
        if mesh_axes:
            size = mesh_axes.get(a)
            if size is None or int(shape[d]) % int(size) != 0:
                out.append(None)
                continue
        seen.add(a)
        out.append(a)
    return tuple(out)


def _spec_str(spec) -> str:
    if spec is None:
        return "?"
    return "<" + ",".join("*" if a is None else str(a) for a in spec) + ">"


def apply_constraint(x, spec):
    """with_sharding_constraint(x) for the active mesh scope — a layout
    hint only: unknown/non-dividing axes are dropped, and ANY failure
    returns x unchanged (constraint emission may never change numerics
    or break a replay)."""
    mesh = current_mesh()
    if mesh is None or spec is None:
        return x
    try:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        shape = tuple(getattr(x, "shape", ()))
        clean = _sanitize(spec, shape, _mesh_axis_sizes(mesh))
        if clean is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec(*clean)))
    except Exception:  # noqa: BLE001 — hints degrade, never break
        return x


# --------------------------------------------------------------------------
# per-op derivation rules (the decision half of GSPMD)
# --------------------------------------------------------------------------

def _dot_dims(op: Operation):
    (lc, rc), (lb, rb) = op.eqn.params["dimension_numbers"]
    lhs_nd = len(op.inputs[0].shape)
    rhs_nd = len(op.inputs[1].shape)
    lfree = [d for d in range(lhs_nd) if d not in lc and d not in lb]
    rfree = [d for d in range(rhs_nd) if d not in rc and d not in rb]
    return (tuple(lc), tuple(rc), tuple(lb), tuple(rb), lfree, rfree,
            lhs_nd, rhs_nd)


def _dot_forward(op: Operation, ls, rs):
    lc, rc, lb, rb, lfree, rfree, lnd, rnd = _dot_dims(op)
    ls = _pad(ls, lnd) or (None,) * lnd
    rs = _pad(rs, rnd) or (None,) * rnd
    out = [ls[bl] if ls[bl] is not None else rs[br]
           for bl, br in zip(lb, rb)]
    out += [ls[d] for d in lfree]
    out += [rs[d] for d in rfree]
    return tuple(out)


def _dot_backward(op: Operation, ospec):
    lc, rc, lb, rb, lfree, rfree, lnd, rnd = _dot_dims(op)
    nb = len(lb)
    ospec = _pad(ospec, nb + len(lfree) + len(rfree))
    ls: list = [None] * lnd
    rs: list = [None] * rnd
    for i, (bl, br) in enumerate(zip(lb, rb)):
        ls[bl] = rs[br] = ospec[i]
    for j, d in enumerate(lfree):
        ls[d] = ospec[nb + j]
    for j, d in enumerate(rfree):
        rs[d] = ospec[nb + len(lfree) + j]
    return tuple(ls), tuple(rs)


def _reduce_axes(op: Operation):
    """Reduced-out dims for single-input rank-dropping reductions
    (reduce_sum & friends carry an ``axes`` param)."""
    if op.eqn is None or len(op.inputs) != 1:
        return None
    axes = op.eqn.params.get("axes")
    if axes is None:
        return None
    axes = tuple(int(a) for a in axes)
    if len(op.outputs) == 1 and \
            len(op.outputs[0].shape) == len(op.inputs[0].shape) - len(axes):
        return axes
    return None


class _Deriver:
    """Forward/backward per-op spec derivation with CostModel conflict
    resolution. Pure over a facts dict keyed by id(Value) — the search
    pass prices candidate strategies through the same machinery without
    touching the program."""

    def __init__(self, cost_model: Optional[CostModel] = None):
        self.cost = cost_model or CostModel()
        self.lattice = FlatLattice()
        self.resolved: list = []

    def _resolve(self, op: Operation, annotated):
        """Pick the winner among clashing operand specs: the candidate
        with the cheapest reshard (bytes of the operands that would have
        to move over the ICI row to agree), ties broken textually."""
        candidates = sorted({spec for _, spec in annotated}, key=repr)

        def reshard_bytes(c):
            return sum(CostModel._value_bytes([v])
                       for v, s in annotated if s != c)
        win = min(candidates, key=lambda c: (reshard_bytes(c), repr(c)))
        self.resolved.append((op, win))
        return win

    def _join_inputs(self, op: Operation, facts: dict):
        annotated = [(v, facts[id(v)]) for v in op.inputs
                     if facts.get(id(v)) is not None]
        if not annotated:
            return None, []
        joined = None
        for _, s in annotated:
            joined = self.lattice.join(joined, s)
        if joined is CONFLICT:
            joined = self._resolve(op, annotated)
        return joined, annotated

    def forward(self, op: Operation, facts: dict) -> bool:
        if all(facts.get(id(o)) is not None for o in op.outputs):
            return False
        prim = op.eqn.primitive.name if op.eqn is not None else op.name
        specs = None
        if prim == "dot_general":
            ls, rs = facts.get(id(op.inputs[0])), facts.get(id(op.inputs[1]))
            if ls is not None or rs is not None:
                specs = [_dot_forward(op, ls, rs)]
        elif prim == "transpose":
            s = facts.get(id(op.inputs[0]))
            if s is not None:
                perm = op.eqn.params["permutation"]
                s = _pad(s, len(op.inputs[0].shape))
                specs = [tuple(s[p] for p in perm)]
        elif prim == "broadcast_in_dim":
            s = facts.get(id(op.inputs[0]))
            if s is not None:
                bd = op.eqn.params["broadcast_dimensions"]
                s = _pad(s, len(op.inputs[0].shape))
                out: list = [None] * len(op.outputs[0].shape)
                for i, d in enumerate(bd):
                    out[d] = s[i]
                specs = [tuple(out)]
        elif _reduce_axes(op) is not None:
            s = facts.get(id(op.inputs[0]))
            if s is not None:
                axes = _reduce_axes(op)
                s = _pad(s, len(op.inputs[0].shape))
                specs = [tuple(a for d, a in enumerate(s) if d not in axes)]
        else:
            # join rule: annotated operands agree (or are resolved), and
            # every output whose shape matches an operand inherits
            joined, annotated = self._join_inputs(op, facts)
            if joined is not None:
                in_shapes = {tuple(v.shape) for v, _ in annotated}
                specs = [joined if tuple(o.shape) in in_shapes
                         or len(joined) == len(o.shape) else None
                         for o in op.outputs]
        if specs is None:
            return False
        if len(specs) == 1 and len(op.outputs) > 1:
            specs = specs * len(op.outputs)
        changed = False
        for o, s in zip(op.outputs, specs):
            if s is None or facts.get(id(o)) is not None:
                continue
            facts[id(o)] = _sanitize(s, o.shape)
            changed = True
        return changed

    def backward(self, op: Operation, facts: dict) -> bool:
        outs = [facts.get(id(o)) for o in op.outputs]
        if all(s is None for s in outs):
            return False
        prim = op.eqn.primitive.name if op.eqn is not None else op.name
        ins = None
        if prim == "dot_general" and outs[0] is not None:
            ins = list(_dot_backward(op, outs[0]))
        elif prim == "transpose" and outs[0] is not None:
            perm = op.eqn.params["permutation"]
            s = _pad(outs[0], len(op.outputs[0].shape))
            inv: list = [None] * len(perm)
            for i, p in enumerate(perm):
                inv[p] = s[i]
            ins = [tuple(inv)]
        elif prim == "broadcast_in_dim" and outs[0] is not None:
            bd = op.eqn.params["broadcast_dimensions"]
            s = _pad(outs[0], len(op.outputs[0].shape))
            ins = [tuple(s[d] for d in bd)]
        elif _reduce_axes(op) is not None and outs[0] is not None:
            axes = _reduce_axes(op)
            s = list(outs[0])
            for d in sorted(axes):
                s.insert(d, None)
            ins = [tuple(s)]
        else:
            # same-shape mirror of the join rule
            by_shape = {tuple(o.shape): s
                        for o, s in zip(op.outputs, outs) if s is not None}
            ins = [by_shape.get(tuple(v.shape)) for v in op.inputs]
        if ins is None:
            return False
        changed = False
        for v, s in zip(op.inputs, ins):
            if s is None or facts.get(id(v)) is not None:
                continue
            facts[id(v)] = _sanitize(s, v.shape)
            changed = True
        return changed


def propagate_facts(prog: Program, seed: dict,
                    cost_model: Optional[CostModel] = None):
    """Run the forward+backward fixpoint over a facts dict (no program
    mutation). Returns ``(facts, stamps, resolved, sweeps)``: stamps is
    ``{id(op): rule_text}`` for every op whose operand/result specs
    legitimately diverge and therefore needs a ``sharding_rule``
    contract for the consistency analysis."""
    deriver = _Deriver(cost_model)
    facts = dict(seed)
    sweeps = 0
    for sweeps in range(1, MAX_SWEEPS + 1):
        changed = False
        for op in prog.ops:
            changed |= deriver.forward(op, facts)
        for op in reversed(prog.ops):
            changed |= deriver.backward(op, facts)
        if not changed:
            break
    lattice = FlatLattice()
    stamps: dict = {}
    for op in prog.ops:
        outs = [facts.get(id(o)) for o in op.outputs]
        ins = [facts.get(id(v)) for v in op.inputs
               if facts.get(id(v)) is not None]
        if not ins or all(s is None for s in outs):
            continue
        joined = None
        for s in ins:
            joined = lattice.join(joined, s)
        if joined is CONFLICT:
            win = deriver._resolve(op, [
                (v, facts[id(v)]) for v in op.inputs
                if facts.get(id(v)) is not None])
            stamps[id(op)] = f"reshard{_spec_str(win)}"
        elif joined is not None and any(
                s is not None and s != joined for s in outs):
            prim = op.eqn.primitive.name if op.eqn is not None else op.name
            stamps[id(op)] = f"{prim}{_spec_str(outs[0])}"
    return facts, stamps, list(deriver.resolved), sweeps


# --------------------------------------------------------------------------
# the pass
# --------------------------------------------------------------------------

class ShardingPropagation(Pass):
    """Fill ``Value.sharding`` from the program-input annotations and
    stamp ``sharding_rule`` contracts where operand specs legitimately
    diverge; pin the scope mesh on the program for constraint emission.
    No annotations anywhere -> no-op (the single-chip fast path costs
    one scan of the inputs). The ``compile.shard_prop`` fault site wraps
    the entry: an injected failure propagates to pipeline.compile_flat,
    which degrades that compile to plain unsharded jax.jit under
    ``pir_fallback_total{stage="passes"}``."""

    name = "shard_prop"

    def run(self, prog: Program) -> PassResult:
        from ..resilience.faults import fault_point
        fault_point("compile.shard_prop", program=prog.name)
        mesh_axes = _mesh_axis_sizes(current_mesh())
        seed: dict = {}
        pinned: set = set()
        for v in list(prog.inputs) + list(prog.constants):
            if v.sharding is not None:
                seed[id(v)] = _sanitize(v.sharding, v.shape, mesh_axes)
        for op in prog.ops:
            for o in op.outputs:
                if o.sharding is not None:
                    # interior pre-stamp: a source for propagation but
                    # never ours to resolve or rule-stamp over — if it
                    # contradicts the flow, the verifier rejects it
                    seed[id(o)] = _sanitize(o.sharding, o.shape, mesh_axes)
                    pinned.add(id(op))
        if not seed:
            return PassResult(0, "no-annotations")
        facts, stamps, resolved, sweeps = propagate_facts(prog, seed)
        values = 0
        for v in self._all_values(prog):
            s = facts.get(id(v))
            if s is not None and v.sharding is None:
                v.sharding = s
                values += 1
        rules = 0
        for op in prog.ops:
            rule = stamps.get(id(op))
            if rule is None or id(op) in pinned \
                    or "sharding_rule" in op.attrs:
                continue
            op.attrs["sharding_rule"] = rule
            rules += 1
            for o in op.outputs:     # contract ops declare every output
                if o.sharding is None:
                    o.sharding = (None,) * len(o.shape)
                    values += 1
        mesh = current_mesh()
        if mesh is not None and (values or seed):
            prog._mesh = mesh        # evaluator traces under this mesh
        if values:
            try:
                from ..observability.catalog import metric as _metric
                _metric("pir_sharding_annotations_total",
                        program=prog.name).inc(values)
            except Exception:  # noqa: BLE001 — metrics never cost a compile
                pass
        return PassResult(
            values + rules,
            f"values={values} rules={rules} resolved={len(resolved)} "
            f"sweeps={sweeps}")

    @staticmethod
    def _all_values(prog: Program):
        for v in prog.inputs:
            yield v
        for v in prog.constants:
            yield v
        for op in prog.ops:
            for o in op.outputs:
                yield o
