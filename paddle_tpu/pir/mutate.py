"""Seeded IR corruptions for mutation-testing the verifier.

Each corruption models one real pass-bug family (operand rewiring gone
wrong, a dropped definition, a forged type stamp, reordered stateful
ops, ...) and names the verifier rule that MUST reject it —
tests/test_pir_verifier.py applies the whole matrix to captured
programs and asserts every one is caught with exactly that rule. A
verifier change that silently stops catching a family fails the matrix,
the same way the chaos drill fails on an escaped fault.

All corruptions mutate the program in place and are seeded
(``random.Random(seed)``) so a failure reproduces exactly. ``corrupt``
raises ``SkipCorruption`` when the program has no viable target (e.g.
no two differently-typed operands to swap) — callers pick fixtures
accordingly.
"""

from __future__ import annotations

import random

from .ir import Program

__all__ = ["CORRUPTIONS", "SkipCorruption", "corrupt"]


class SkipCorruption(Exception):
    """The program offers no target for this corruption."""


def _rng(seed):
    return random.Random(f"pir-mutate:{seed}")


def _swap_operands(prog: Program, rng) -> str:
    """A rewrite wired an op's operands in the wrong order. Pick an eqn
    op with two operands of different type so the swap is a *type*
    error (same-typed swaps are value bugs the replay fallback owns)."""
    cands = []
    for op in prog.ops:
        if op.eqn is None:
            continue
        for i in range(len(op.inputs)):
            for j in range(i + 1, len(op.inputs)):
                a, b = op.inputs[i], op.inputs[j]
                if (a.shape, str(a.dtype)) != (b.shape, str(b.dtype)):
                    cands.append((op, i, j))
    if not cands:
        raise SkipCorruption("no op with differently-typed operands")
    op, i, j = rng.choice(cands)
    op.inputs[i], op.inputs[j] = op.inputs[j], op.inputs[i]
    return f"swapped operands {i}<->{j} of {op.name!r}"


def _drop_def(prog: Program, rng) -> str:
    """A pass deleted an op whose results are still consumed."""
    users = prog.users()
    cands = [op for op in prog.ops
             if any(u is not None
                    for o in op.outputs for u in users.get(o, ()))]
    if not cands:
        raise SkipCorruption("no op with op-consumed results")
    op = rng.choice(cands)
    prog.ops.remove(op)
    return f"dropped defining op {op.name!r}"


def _forge_dtype(prog: Program, rng) -> str:
    """A rewrite stamped the wrong dtype on a result Value."""
    cands = [o for op in prog.ops if op.eqn is not None
             for o in op.outputs]
    if not cands:
        raise SkipCorruption("no eqn-op results")
    v = rng.choice(cands)
    import numpy as np
    forged = np.dtype("int16") if str(v.dtype) != "int16" \
        else np.dtype("float64")
    v.dtype = forged
    return f"forged dtype of %{v.vid} to {forged}"


def _double_def(prog: Program, rng) -> str:
    """A buggy merge made a second op claim an existing Value."""
    if len(prog.ops) < 2:
        raise SkipCorruption("fewer than two ops")
    i = rng.randrange(len(prog.ops) - 1)
    j = rng.randrange(i + 1, len(prog.ops))
    val_a = prog.ops[i].outputs[0]
    prog.ops[j].outputs[0] = val_a
    return f"{prog.ops[j].name!r} re-defines %{val_a.vid}"


def _bad_arity(prog: Program, rng) -> str:
    """An operand list lost an entry during rewiring."""
    cands = [op for op in prog.ops
             if op.eqn is not None and len(op.inputs) >= 1]
    if not cands:
        raise SkipCorruption("no eqn op with operands")
    op = rng.choice(cands)
    op.inputs.pop()
    return f"dropped the last operand of {op.name!r}"


def _dangling_output(prog: Program, rng) -> str:
    """A program output points at a Value nothing defines."""
    if not prog.outputs:
        raise SkipCorruption("no program outputs")
    i = rng.randrange(len(prog.outputs))
    old = prog.outputs[i]
    prog.outputs[i] = prog.new_value(old.shape, old.dtype)
    return f"output {i} replaced with an undefined value"


def _reorder_kv_write(prog: Program, rng) -> str:
    """A pass reordered stateful paged-KV ops: swap the captured
    effect_seq stamps of two effect ops (equivalently, the ops moved
    past each other in program order)."""
    eff = [op for op in prog.ops if op.attrs.get("effect") is not None]
    if len(eff) < 2:
        raise SkipCorruption("fewer than two effect-stamped ops")
    a, b = rng.sample(eff, 2)
    a.attrs["effect_seq"], b.attrs["effect_seq"] = \
        b.attrs["effect_seq"], a.attrs["effect_seq"]
    return (f"swapped effect_seq of {a.name!r} and {b.name!r} "
            f"({a.attrs['effect']}/{b.attrs['effect']})")


def _sharding_clash(prog: Program, rng) -> str:
    """A propagation bug committed output shardings across an op whose
    operands irreconcilably disagree — without declaring the
    ``sharding_rule`` contract that would make the divergence legal.
    (Annotating only the operands is NOT a corruption: that is the
    legitimate pending state between annotate_inputs and the
    shard_prop pass.)"""
    cands = [op for op in prog.ops
             if len(op.inputs) >= 2 and op.outputs
             and op.inputs[0] is not op.inputs[1]]
    if not cands:
        raise SkipCorruption("no op with two distinct operands")
    op = rng.choice(cands)
    op.inputs[0].sharding = ("data", None)
    op.inputs[1].sharding = ("model", None)
    for o in op.outputs:
        o.sharding = ("data",) + (None,) * max(0, len(o.shape) - 1)
    return (f"committed output shardings of {op.name!r} over clashing "
            f"operand annotations")


def _sharding_rule_forge(prog: Program, rng) -> str:
    """A half-applied propagation stamp: an op claims a
    ``sharding_rule`` boundary (operands may legally diverge there) but
    its outputs never received the annotations the contract requires —
    the forged stamp must not silence the consistency check."""
    cands = [op for op in prog.ops
             if op.outputs and not op.attrs.get("sharding_rule")]
    if not cands:
        raise SkipCorruption("no op to stamp")
    op = rng.choice(cands)
    op.attrs["sharding_rule"] = "forged(data,model)"
    for o in op.outputs:
        o.sharding = None
    # make the check reachable: some annotation must exist in the
    # program for the verifier to engage the sharding analysis at all
    if op.inputs:
        op.inputs[0].sharding = \
            ("data",) + (None,) * max(0, len(op.inputs[0].shape) - 1)
    elif prog.inputs:
        prog.inputs[0].sharding = \
            ("data",) + (None,) * max(0, len(prog.inputs[0].shape) - 1)
    return f"stamped forged sharding_rule on {op.name!r} with bare outputs"


# corruption name -> (mutator, verifier rule that must reject it)
CORRUPTIONS = {
    "swap-operands": (_swap_operands, "type-mismatch"),
    "drop-def": (_drop_def, "def-before-use"),
    "forge-dtype": (_forge_dtype, "type-mismatch"),
    "double-def": (_double_def, "single-def"),
    "bad-arity": (_bad_arity, "arity"),
    "dangling-output": (_dangling_output, "dangling-value"),
    "reorder-kv-write": (_reorder_kv_write, "effect-order"),
    "sharding-clash": (_sharding_clash, "sharding-conflict"),
    "sharding-rule-forge": (_sharding_rule_forge, "sharding-conflict"),
}


def corrupt(prog: Program, kind: str, seed: int = 0) -> str:
    """Apply one seeded corruption in place; returns a description.
    Unknown kinds raise KeyError (closed registry)."""
    mutator, _expected_rule = CORRUPTIONS[kind]
    return mutator(prog, _rng(seed))
