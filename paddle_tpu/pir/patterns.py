"""DRR-lite pattern rewriting.

reference: paddle/fluid/pir/drr/ — declarative rewrite rules: a source
pattern (a small graph of ops + constraint functions) and a result
pattern (one fused op). This is the -lite edition: patterns are Python
classes with an explicit ``match`` (structural walk + constraints over
folded constants) and ``rewrite`` (splice one fused Operation whose
callable routes between the hand-written kernel and a byte-faithful
replay of the matched region).

Production patterns:

* ``sdpa_route`` — the scaled-dot-product-attention subgraph
  (QK dot_general -> scale -> causal mask -> softmax -> PV dot_general)
  becomes one ``pt.sdpa`` op that dispatches through the per-shape
  attention backend router (ops/pallas/attention_router): Pallas flash
  on TPU where the baked ledger says it wins, otherwise an exact replay
  of the captured region (identical numerics by construction).
* ``rms_epilogue`` — ``rmsnorm(pt.sdpa + residual) * gamma`` becomes
  ``pt.sdpa_rms_epilogue``, dispatching to
  ``flash_attention_rms_epilogue_bshd`` (the attention output never
  round-trips HBM unnormalized) where routed, else replay.

Constraint discipline: a pattern only fires when it can *prove* the
structure — e.g. causality is established by constant-folding the mask
subgraph (the fold pass runs first) and comparing against tril(ones),
never by guessing from op names.
"""

from __future__ import annotations

from typing import Optional

from .ir import Operation, Program
from .passes import Pass, PassResult

__all__ = ["RewritePattern", "PatternRewriter", "SdpaRoutePattern",
           "RmsEpiloguePattern", "region_replay"]

# ops the matcher walks through when following an edge (layout/dtype
# plumbing that does not change the math being matched)
_PASSTHROUGH = ("broadcast_in_dim", "convert_element_type", "reshape",
                "stop_gradient")


def region_replay(prog, region_ops, boundary_in, out_value):
    """Build a callable replaying `region_ops` from the boundary values:
    the fused op's mathematically-exact fallback path. Ops run in
    program (topological) order; constants are snapshotted now (a later
    DCE pruning the originals must not break the replay). Fused ops
    inside the region (pattern-over-pattern) replay through their own
    fn."""
    rid = set(map(id, region_ops))
    ordered = [op for op in prog.ops if id(op) in rid]
    const_env = {id(v): c for v, c in prog.constants.items()}

    def replay(*args):
        env = dict(const_env)
        for v, a in zip(boundary_in, args):
            env[id(v)] = a
        for op in ordered:
            ins = [env[id(v)] for v in op.inputs]
            for v, o in zip(op.outputs, op.evaluate(ins)):
                env[id(v)] = o
        return env[id(out_value)]

    return replay


class RewritePattern:
    name = "pattern"

    def match(self, prog: Program, op: Operation, users: dict):
        raise NotImplementedError

    def rewrite(self, prog: Program, m: dict) -> Operation:
        raise NotImplementedError


# --------------------------------------------------------------------------
# matching helpers
# --------------------------------------------------------------------------

def _is_const(prog, v):
    return v in prog.constants


def _const_of(prog, v):
    import numpy as np
    return np.asarray(prog.constants[v])


def _walk_up(v, names, collect):
    """Follow defining ops up through `names`, collecting them; returns
    the first value whose producer is not in `names`."""
    while v.op is not None and v.op.name in names:
        collect.append(v.op)
        # pass-throughs are single-math-input ops; pick the non-const
        # operand when an op like max(scalar, x) carries a bound
        ins = v.op.inputs
        v = ins[0] if len(ins) == 1 else next(
            (x for x in ins if x.op is not None or x.shape), ins[0])
    return v

def _sole_user(users, v, skip_none=False):
    us = [u for u in users.get(v, []) if not (skip_none and u is None)]
    return us[0] if len(us) == 1 and us[0] is not None else None


def _region_closed(users, region_ops, outs_allowed):
    """Every value produced inside the region is consumed only inside
    it, except the designated outputs."""
    rid = set(map(id, region_ops))
    allowed = set(map(id, outs_allowed))
    for op in region_ops:
        for o in op.outputs:
            if id(o) in allowed:
                continue
            for u in users.get(o, []):
                if u is None or id(u) not in rid:
                    return False
    return True


def _route_decision(bh, sq, sk, d, dtype, causal):
    try:
        from ..ops.pallas.attention_router import route
        return route(int(bh), int(sq), int(sk), int(d), dtype, bool(causal))
    except Exception:  # noqa: BLE001 — no ledger/router: replay-only op
        return None


def _on_tpu():
    import jax
    return jax.default_backend() == "tpu"


# --------------------------------------------------------------------------
# sdpa -> routed attention backend
# --------------------------------------------------------------------------

_QK_DIMS = (((3,), (3,)), ((0, 2), (0, 2)))       # bshd x bshd -> bhqk
_PV_DIMS_VP = (((1,), (3,)), ((0, 2), (0, 1)))    # v as lhs, probs as rhs
_PV_DIMS_PV = (((3,), (1,)), ((0, 1), (0, 2)))    # probs as lhs, v as rhs


class SdpaRoutePattern(RewritePattern):
    name = "sdpa_route"

    def match(self, prog, op, users):
        import numpy as np
        if op.name != "div" or len(op.inputs) != 2:
            return None
        num, den = op.inputs
        exp_op = num.op
        if exp_op is None or exp_op.name != "exp":
            return None
        # denominator: reduce_sum(exp) through broadcasts
        chain_d: list = []
        dv = _walk_up(den, _PASSTHROUGH, chain_d)
        sum_op = dv.op
        if sum_op is None or sum_op.name != "reduce_sum" \
                or sum_op.inputs[0] is not exp_op.outputs[0] \
                or tuple(sum_op.eqn.params.get("axes") or ()) != (3,):
            return None
        # exp input: sub(logits, reduce_max(logits) [through guards])
        sub_op = exp_op.inputs[0].op
        if sub_op is None or sub_op.name != "sub":
            return None
        logits = sub_op.inputs[0]
        chain_m: list = []
        mv = _walk_up(sub_op.inputs[1], _PASSTHROUGH + ("max",), chain_m)
        max_op = mv.op
        if max_op is None or max_op.name != "reduce_max" \
                or max_op.inputs[0] is not logits \
                or tuple(max_op.eqn.params.get("axes") or ()) != (3,):
            return None

        softmax_ops = [exp_op, sub_op, sum_op, op, max_op] + chain_d + chain_m

        # upstream: optional where-mask, then optional scale-mul, then QK dot
        region = list(softmax_ops)
        causal = False
        cur = logits
        prod = cur.op
        mask_sq_sk = None
        if prod is not None and (
                prod.name == "select_n"
                or (prod.name == "pjit"
                    and prod.eqn.params.get("name") == "_where")):
            consts = [v for v in prod.inputs if _is_const(prog, v)]
            lives = [v for v in prod.inputs if not _is_const(prog, v)]
            if len(lives) != 1 or len(consts) != 2:
                return None
            mask_v = next((v for v in consts
                           if _const_of(prog, v).ndim == 2), None)
            fill_v = next((v for v in consts
                           if _const_of(prog, v).ndim == 0), None)
            if mask_v is None or fill_v is None:
                return None
            if float(_const_of(prog, fill_v)) > -1e9:
                return None
            mask = _const_of(prog, mask_v).astype(bool)
            sq, sk = mask.shape
            if not np.array_equal(
                    mask, np.tril(np.ones((sq, sk), bool), k=sk - sq)):
                return None           # only provable-causal masks rewrite
            causal = True
            mask_sq_sk = (sq, sk)
            region.append(prod)
            cur = lives[0]
            prod = cur.op
        scale = 1.0
        if prod is not None and prod.name in ("mul", "div"):
            sc = next((v for v in prod.inputs if _is_const(prog, v)
                       and _const_of(prog, v).ndim == 0), None)
            live = next((v for v in prod.inputs if not _is_const(prog, v)),
                        None)
            if sc is None or live is None:
                return None
            if prod.name == "div":
                if prod.inputs[0] is not live:     # const/x is not a scale
                    return None
                scale = 1.0 / float(_const_of(prog, sc))
            else:
                scale = float(_const_of(prog, sc))
            region.append(prod)
            cur = live
            prod = cur.op
        if prod is None or prod.name != "dot_general":
            return None
        qk = prod
        if qk.eqn.params.get("dimension_numbers") != _QK_DIMS:
            return None
        q, k = qk.inputs
        if len(q.shape) != 4 or len(k.shape) != 4:
            return None
        b, sq_, h, d = q.shape
        sk_ = k.shape[1]
        if k.shape[0] != b or k.shape[2] != h or k.shape[3] != d:
            return None
        if causal and mask_sq_sk != (sq_, sk_):
            return None
        region.append(qk)

        # downstream: probs (-> convert) -> PV dot_general -> transpose
        probs = op.outputs[0]
        pv_in = probs
        down: list = []
        u = _sole_user(users, pv_in)
        if u is not None and u.name == "convert_element_type":
            down.append(u)
            pv_in = u.outputs[0]
            u = _sole_user(users, pv_in)
        if u is None or u.name != "dot_general":
            return None
        pv = u
        dims = pv.eqn.params.get("dimension_numbers")
        if pv.inputs[1] is pv_in and dims == _PV_DIMS_VP:
            v_val = pv.inputs[0]
            want_perm = (0, 3, 1, 2)     # (b,h,d,q) -> (b,q,h,d)
        elif pv.inputs[0] is pv_in and dims == _PV_DIMS_PV:
            v_val = pv.inputs[1]
            want_perm = (0, 2, 1, 3)     # (b,h,q,d) -> (b,q,h,d)
        else:
            return None
        if v_val.shape[:3] != (b, sk_, h):
            return None
        down.append(pv)
        tr = _sole_user(users, pv.outputs[0])
        if tr is None or tr.name != "transpose" \
                or tuple(tr.eqn.params.get("permutation") or ()) != want_perm:
            return None
        down.append(tr)
        out_val = tr.outputs[0]
        if out_val.shape != (b, sq_, h, v_val.shape[3]):
            return None
        region += down
        if not _region_closed(users, region, [out_val]):
            return None
        return {"region": region, "q": q, "k": k, "v": v_val,
                "out": out_val, "causal": causal, "scale": scale,
                "shape": (b, sq_, sk_, h, d)}

    def rewrite(self, prog, m):
        b, sq, sk, h, d = m["shape"]
        q, k, v, out = m["q"], m["k"], m["v"], m["out"]
        causal, scale = m["causal"], m["scale"]
        dec = _route_decision(b * h, sq, sk, d, q.dtype, causal)
        replay = region_replay(prog, m["region"], [q, k, v], out)
        route_fwd = dec.fwd if dec is not None else "replay"

        def fn(q_, k_, v_):
            if route_fwd == "pallas" and _on_tpu():
                from ..ops.pallas.flash_attention import flash_attention_bshd
                return flash_attention_bshd(q_, k_, v_, causal=causal,
                                            scale=scale)
            return replay(q_, k_, v_)

        new_op = Operation(
            "pt.sdpa", [q, k, v], [out],
            attrs={"causal": causal, "scale": scale, "route_fwd": route_fwd,
                   "route_source": getattr(dec, "source", "none"),
                   "shape": (b, sq, sk, h, d)},
            fn=fn)
        prog.replace_region(m["region"], new_op)
        return new_op


# --------------------------------------------------------------------------
# rmsnorm(sdpa + residual) * gamma -> fused epilogue
# --------------------------------------------------------------------------

class RmsEpiloguePattern(RewritePattern):
    """Anchors on a ``pt.sdpa`` produced by SdpaRoutePattern (pattern-
    over-pattern: DRR result ops are legal source ops)."""

    name = "rms_epilogue"

    def match(self, prog, op, users):
        if op.name != "pt.sdpa":
            return None
        att = op.outputs[0]
        add = _sole_user(users, att)
        if add is None or add.name != "add":
            return None
        residual = add.inputs[1] if add.inputs[0] is att else add.inputs[0]
        region = [op, add]
        hh = add.outputs[0]
        cv = _sole_user(users, hh)
        if cv is not None and cv.name == "convert_element_type":
            region.append(cv)
            hh = cv.outputs[0]
        hh_users = [u for u in users.get(hh, []) if u is not None]
        sq_op = next((u for u in hh_users if u.name == "mul"
                      and u.inputs[0] is hh and u.inputs[1] is hh), None)
        if sq_op is None:
            return None
        region.append(sq_op)
        rs = _sole_user(users, sq_op.outputs[0])
        if rs is None or rs.name != "reduce_sum":
            return None
        axes = rs.eqn.params.get("axes")
        if tuple(axes or ()) != (len(hh.shape) - 1,):
            return None                     # norm axis must be head dim
        region.append(rs)
        # mean = sum/d (div by const), then + eps, rsqrt
        chain: list = []
        cur_op = _sole_user(users, rs.outputs[0])
        d = hh.shape[-1]
        saw_div = saw_eps = False
        eps = 0.0
        import numpy as np
        while cur_op is not None and cur_op.name in (
                "div", "mul", "add", "broadcast_in_dim", "reshape",
                "convert_element_type"):
            if cur_op.name in ("div", "mul", "add"):
                sc = next((v for v in cur_op.inputs if _is_const(prog, v)
                           and _const_of(prog, v).ndim == 0), None)
                if sc is None:
                    return None
                val = float(_const_of(prog, sc))
                if cur_op.name == "div" and abs(val - d) < 0.5:
                    saw_div = True
                elif cur_op.name == "mul" and abs(val - 1.0 / d) < 1e-12:
                    saw_div = True
                elif cur_op.name == "add":
                    saw_eps, eps = True, val
                else:
                    return None
            chain.append(cur_op)
            cur_op = _sole_user(users, cur_op.outputs[0])
        if cur_op is None or cur_op.name != "rsqrt" \
                or not (saw_div and saw_eps):
            return None
        region += chain + [cur_op]
        inv = cur_op.outputs[0]
        bchain: list = []
        nv = inv
        u = _sole_user(users, nv)
        while u is not None and u.name in ("broadcast_in_dim", "reshape",
                                           "convert_element_type"):
            bchain.append(u)
            nv = u.outputs[0]
            u = _sole_user(users, nv)
        norm_mul = u
        if norm_mul is None or norm_mul.name != "mul" \
                or hh not in norm_mul.inputs:
            return None
        region += bchain + [norm_mul]
        # * gamma: mul with a broadcast of a rank-1 weight value
        wmul = _sole_user(users, norm_mul.outputs[0])
        if wmul is None or wmul.name != "mul":
            return None
        wside = (wmul.inputs[1] if wmul.inputs[0] is norm_mul.outputs[0]
                 else wmul.inputs[0])
        wchain: list = []
        w_val = _walk_up(wside, _PASSTHROUGH, wchain)
        if len(w_val.shape) != 1 or w_val.shape[0] != hh.shape[-1]:
            return None
        region += wchain + [wmul]
        out_val = wmul.outputs[0]
        u = _sole_user(users, out_val)
        if u is not None and u.name == "convert_element_type":
            region.append(u)
            out_val = u.outputs[0]
        if not _region_closed(users, region, [out_val]):
            return None
        return {"region": region, "q": op.inputs[0], "k": op.inputs[1],
                "v": op.inputs[2], "residual": residual, "w": w_val,
                "out": out_val, "eps": eps, "sdpa": op}

    def rewrite(self, prog, m):
        sdpa = m["sdpa"]
        causal = sdpa.attrs["causal"]
        scale = sdpa.attrs["scale"]
        b, sq, sk, h, d = sdpa.attrs["shape"]
        eps = m["eps"]
        q, k, v, residual, w = (m["q"], m["k"], m["v"], m["residual"],
                                m["w"])
        dec = _route_decision(b * h, sq, sk, d, q.dtype, causal)
        route_fwd = dec.fwd if dec is not None else "replay"
        replay = region_replay(prog, m["region"],
                               [q, k, v, residual, w], m["out"])
        out_dtype = m["out"].dtype

        def fn(q_, k_, v_, res_, w_):
            if route_fwd == "pallas" and _on_tpu():
                from ..ops.pallas.flash_attention import (
                    flash_attention_rms_epilogue_bshd)
                out = flash_attention_rms_epilogue_bshd(
                    q_, k_, v_, res_, w_, causal=causal, scale=scale,
                    eps=eps)
                return out.astype(out_dtype)
            return replay(q_, k_, v_, res_, w_)

        new_op = Operation(
            "pt.sdpa_rms_epilogue", [q, k, v, residual, w], [m["out"]],
            attrs={"causal": causal, "scale": scale, "eps": eps,
                   "route_fwd": route_fwd,
                   "route_source": getattr(dec, "source", "none"),
                   "shape": (b, sq, sk, h, d)},
            fn=fn)
        prog.replace_region(m["region"], new_op)
        return new_op


# --------------------------------------------------------------------------
# the pass
# --------------------------------------------------------------------------

_MAX_REWRITES = 64


class PatternRewriter(Pass):
    """Apply all registered patterns to fixpoint (bounded). Each applied
    rewrite is one edit; per-pattern counts go in the notes."""

    name = "pattern"

    def __init__(self, patterns: Optional[list] = None):
        self.patterns = (list(patterns) if patterns is not None
                         else [SdpaRoutePattern(), RmsEpiloguePattern()])

    def run(self, prog: Program) -> PassResult:
        counts: dict[str, int] = {}
        total = 0
        progress = True
        while progress and total < _MAX_REWRITES:
            progress = False
            for pat in self.patterns:
                users = prog.users()
                for op in prog.ops:
                    m = pat.match(prog, op, users)
                    if m is None:
                        continue
                    pat.rewrite(prog, m)
                    counts[pat.name] = counts.get(pat.name, 0) + 1
                    total += 1
                    progress = True
                    break   # program changed: rescan with fresh users
        notes = " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        return PassResult(total, notes or "no-match")
