"""Dataflow analysis framework over pir.Program.

reference: paddle/pir/include/pass/analysis_manager.h (pir analyses
feeding passes) — here a generic join-semilattice worklist engine over
the straight-line SSA op list, so future passes (the ROADMAP's
GSPMD-style sharding propagation, collective-overlap scheduling) are
written as pure transfer functions instead of ad-hoc graph walks.

Three concrete analyses ship with the framework:

* **ShapeDtypeInference** (forward): re-derives every Value's abstract
  type from the program inputs/constants — eqn-backed ops from the
  jaxpr avals they replay, fused ``pt.*`` ops through ``jax.eval_shape``
  of their callable. Backs the verifier's ``type-mismatch`` rule.
* **Liveness** (backward): live-Value sets per program point plus
  use/def indices; feeds ``check_donation_safety`` which statically
  rejects the donated-double-buffer hazard COMPILER.md previously only
  documented (a donated buffer read again after the in-place-style op
  that aliases over it).
* **ShardingConsistency** (forward): propagates optional per-Value
  sharding annotations (``Value.sharding``) and reports conflicts —
  the seed of the sharding-propagation pass: that pass will *choose*
  shardings; this analysis already proves a chosen assignment coherent.

Programs here are topologically-ordered straight-line SSA (no control
flow at this level — scans/whiles are single ops), so the fixpoint
converges in one sweep; the worklist engine still re-enqueues dependents
so transfer functions may be written without ordering assumptions.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Optional

from .ir import Operation, Program, Value

__all__ = ["Lattice", "FlatLattice", "DataflowAnalysis",
           "ShapeDtypeInference", "Liveness", "ShardingConsistency",
           "DonationHazard", "check_donation_safety", "CONFLICT",
           "CostModel", "ProgramCost", "OpCost", "DEFAULT_ROOFLINE",
           "DEFAULT_INTERCONNECT"]


class _Conflict:
    """Lattice top: irreconcilable facts met."""

    def __repr__(self):
        return "<CONFLICT>"


CONFLICT = _Conflict()


class Lattice:
    """Join-semilattice interface: ``bottom`` (no information) joined
    upward toward ``CONFLICT`` (contradictory information)."""

    def bottom(self):
        return None

    def join(self, a, b):  # pragma: no cover - interface
        raise NotImplementedError


class FlatLattice(Lattice):
    """bottom (None) < any concrete fact < CONFLICT. Two distinct
    concrete facts join to CONFLICT — the shape every annotation-
    consistency analysis (sharding, layout, memory space) starts from."""

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if a is CONFLICT or b is CONFLICT:
            return CONFLICT
        return a if a == b else CONFLICT


class DataflowAnalysis:
    """Worklist fixpoint over a Program's op list.

    Subclasses set ``direction`` ("forward" | "backward") and implement
    ``boundary(prog)`` (seed facts) and ``transfer(op, facts)`` which
    updates ``facts`` in place and returns True when anything changed.
    ``run`` returns the fact map after convergence. Facts are keyed by
    ``id(Value)`` (or anything else the subclass chooses — the engine
    only re-enqueues dependent ops on change).
    """

    direction = "forward"
    name = "analysis"

    def boundary(self, prog: Program) -> dict:
        return {}

    def transfer(self, op: Operation, facts: dict) -> bool:
        raise NotImplementedError  # pragma: no cover - interface

    def run(self, prog: Program) -> dict:
        facts = self.boundary(prog)
        forward = self.direction == "forward"
        order = prog.ops if forward else list(reversed(prog.ops))
        # dependents: forward -> ops consuming my outputs; backward ->
        # ops defining my inputs
        users = prog.users()
        dependents: dict[int, list[Operation]] = {}
        for op in prog.ops:
            if forward:
                deps = [u for o in op.outputs for u in users.get(o, ())
                        if u is not None]
            else:
                deps = [v.op for v in op.inputs if v.op is not None]
            dependents[id(op)] = deps
        worklist = deque(order)
        queued = {id(op) for op in order}
        steps = 0
        budget = max(16, len(prog.ops)) * 8    # straight-line: 1 sweep;
        while worklist:                        # budget guards bad transfers
            op = worklist.popleft()
            queued.discard(id(op))
            steps += 1
            if steps > budget:
                raise RuntimeError(
                    f"dataflow analysis {self.name!r} did not converge "
                    f"on {prog.name!r} within {budget} steps")
            if self.transfer(op, facts):
                for dep in dependents[id(op)]:
                    if id(dep) not in queued:
                        worklist.append(dep)
                        queued.add(id(dep))
        return facts


# --------------------------------------------------------------------------
# shape/dtype inference
# --------------------------------------------------------------------------

class ShapeDtypeInference(DataflowAnalysis):
    """facts: id(Value) -> (shape tuple, dtype str). Inputs/constants
    seed from their stamped types (the program boundary is trusted);
    eqn ops derive outputs from the replayed jaxpr's avals; fused ops
    re-derive through jax.eval_shape of the fused callable (cached per
    op). The verifier compares these derived facts against the stamped
    ``Value.shape/dtype`` (rule ``type-mismatch``)."""

    direction = "forward"
    name = "shape_dtype"

    def __init__(self):
        self._fused_cache: dict[int, Optional[list]] = {}

    @staticmethod
    def _key(shape, dtype):
        return (tuple(shape), str(dtype))

    def boundary(self, prog: Program) -> dict:
        facts = {}
        for v in prog.inputs:
            facts[id(v)] = self._key(v.shape, v.dtype)
        for v in prog.constants:
            facts[id(v)] = self._key(v.shape, v.dtype)
        return facts

    def derived_out_types(self, op: Operation, facts: dict):
        """[(shape, dtype_str)] for op's outputs, or None when underived
        (fused op whose abstract eval is unavailable)."""
        if op.eqn is not None:
            return [self._key(tuple(getattr(ov.aval, "shape", ())),
                              getattr(ov.aval, "dtype", None))
                    for ov in op.eqn.outvars]
        cached = self._fused_cache.get(id(op), False)
        if cached is not False:
            return cached
        import jax
        try:
            in_avals = [jax.ShapeDtypeStruct(facts[id(v)][0],
                                             facts[id(v)][1])
                        for v in op.inputs]
            outs = jax.eval_shape(lambda *a: op.evaluate(list(a)), *in_avals)
            derived = [self._key(o.shape, o.dtype) for o in outs]
        except Exception:  # noqa: BLE001 — an un-abstractable fused op
            derived = None  # just opts out of derivation (stays checkable
        self._fused_cache[id(op)] = derived   # structurally, not by type)
        return derived

    def derived_in_types(self, op: Operation):
        """Expected operand types, or None (only eqn ops pin operands)."""
        if op.eqn is None:
            return None
        return [self._key(tuple(getattr(iv.aval, "shape", ())),
                          getattr(iv.aval, "dtype", None))
                for iv in op.eqn.invars]

    def transfer(self, op: Operation, facts: dict) -> bool:
        if any(id(v) not in facts for v in op.inputs):
            return False            # operands not yet derived
        derived = self.derived_out_types(op, facts)
        if derived is None:
            derived = [self._key(o.shape, o.dtype) for o in op.outputs]
        changed = False
        for v, d in zip(op.outputs, derived):
            if facts.get(id(v)) != d:
                facts[id(v)] = d
                changed = True
        return changed


# --------------------------------------------------------------------------
# liveness + donation safety
# --------------------------------------------------------------------------

class Liveness(DataflowAnalysis):
    """Backward liveness. After ``run``, facts map ``("after", i)`` (op
    index) -> frozenset of Value ids live *after* op i executes; the
    boundary ``("after", len(ops)-1)``... is seeded from the program
    outputs. Also exposes ``last_use``/``uses`` index maps (computed in
    run()) for clients that want ranges rather than sets."""

    direction = "backward"
    name = "liveness"

    def __init__(self):
        self.index: dict[int, int] = {}
        self.uses: dict[int, list[int]] = {}       # id(Value) -> op idxs
        self.last_use: dict[int, int] = {}         # id(Value) -> op idx

    def boundary(self, prog: Program) -> dict:
        self.index = {id(op): i for i, op in enumerate(prog.ops)}
        self.uses = {}
        for i, op in enumerate(prog.ops):
            for v in op.inputs:
                self.uses.setdefault(id(v), []).append(i)
        self.last_use = {vid: idxs[-1] for vid, idxs in self.uses.items()}
        out_live = frozenset(id(v) for v in prog.outputs)
        n = len(prog.ops)
        facts = {("after", n - 1): out_live} if n else {}
        facts["exit"] = out_live
        return facts

    def transfer(self, op: Operation, facts: dict) -> bool:
        i = self.index[id(op)]
        live_after = facts.get(("after", i), frozenset())
        live_before = (live_after - {id(o) for o in op.outputs}) \
            | {id(v) for v in op.inputs}
        changed = False
        if facts.get(("before", i)) != live_before:
            facts[("before", i)] = live_before
            changed = True
        if i > 0:
            prev = facts.get(("after", i - 1), frozenset())
            merged = prev | live_before
            if merged != prev:
                facts[("after", i - 1)] = merged
                changed = True
        return changed


# ops that alias an operand's buffer into a same-typed output under
# donation — the "in-place" shapes XLA folds a donated input into. A
# donated Value must be DEAD after the first of these consumes it;
# elementwise reuse (x*2) is not an overwrite and stays unrestricted.
_OVERWRITE_OPS = ("dynamic_update_slice", "dynamic-update-slice",
                  "scatter", "scatter-add", "scatter_add", "scan", "while")


class DonationHazard:
    __slots__ = ("value", "overwrite_op", "overwrite_index", "use_index")

    def __init__(self, value, overwrite_op, overwrite_index, use_index):
        self.value = value
        self.overwrite_op = overwrite_op
        self.overwrite_index = overwrite_index
        self.use_index = use_index

    def __repr__(self):
        return (f"DonationHazard(%{self.value.vid} overwritten by "
                f"{self.overwrite_op.name!r} at op {self.overwrite_index}, "
                f"read again at op {self.use_index})")


def check_donation_safety(prog: Program, donate_argnums) -> list:
    """Statically reject the donated-double-buffer hazard: a donated
    program input consumed by an overwrite-shaped op (its buffer aliased
    into a same-shape/dtype output) and then *read again* later — on
    device the second read would see the overwritten buffer. Returns
    [DonationHazard]; empty = safe. The real serving decode programs
    pass (each donated KV pool feeds exactly its fused scan, last use ==
    overwrite point)."""
    lv = Liveness()
    lv.run(prog)
    hazards = []
    for argnum in donate_argnums or ():
        if argnum >= len(prog.inputs):
            continue
        d = prog.inputs[argnum]
        use_idxs = lv.uses.get(id(d), [])
        if len(use_idxs) < 2:
            continue                    # single consumer: trivially safe
        for i in use_idxs:
            op = prog.ops[i]
            bare = op.name.split(".")[-1]
            if op.name not in _OVERWRITE_OPS and bare not in _OVERWRITE_OPS:
                continue
            dkey = (tuple(d.shape), str(d.dtype))
            if not any((tuple(o.shape), str(o.dtype)) == dkey
                       for o in op.outputs):
                continue
            later = [j for j in use_idxs if j > i]
            if later:
                hazards.append(DonationHazard(d, op, i, later[0]))
                break
    return hazards


# --------------------------------------------------------------------------
# sharding-annotation consistency
# --------------------------------------------------------------------------

# --------------------------------------------------------------------------
# static cost model (FLOPs / bytes / roofline seconds)
# --------------------------------------------------------------------------

# PR 1 hardware ledger numbers (ops/pallas/attention_router.py _PROXY /
# attention_ledger.json, TPU v5 lite): peak dense throughput, the
# measured dense-matmul efficiency fraction, and HBM bandwidth. Kept as
# a literal so the analysis stays importable without the router.
DEFAULT_ROOFLINE = {
    "peak_flops": 197e12,
    "efficiency": 0.068,
    "hbm_bps": 820e9,
}

# Interconnect row of the same baked ledger (TPU v5 lite ICI): effective
# per-direction link bandwidth and per-collective launch latency. Feeds
# the CostModel's exposed-communication term — comm seconds for a
# collective-bearing op are wire_bytes / ici_bps + latency, and compute
# scheduled between the collective and its first consumer earns overlap
# credit against them (pir/overlap.py maximizes that credit).
DEFAULT_INTERCONNECT = {
    "ici_bps": 4.5e10,
    "link_latency_s": 1e-6,
}

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "float8_e4m3fn": 1,
    "float8_e5m2": 1,
}


def _numel(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _aval_bytes(aval):
    return _numel(getattr(aval, "shape", ())) \
        * _DTYPE_BYTES.get(str(getattr(aval, "dtype", "float32")), 4)


def _inner_jaxprs(params):
    """Closed/open jaxprs nested in an eqn's params (scan's `jaxpr`,
    while's cond/body, pjit's `jaxpr`, custom-call `call_jaxpr`, ...)."""
    found = []
    for v in params.values():
        inner = getattr(v, "jaxpr", None)      # ClosedJaxpr
        if inner is not None and hasattr(inner, "eqns"):
            found.append(inner)
        elif hasattr(v, "eqns"):               # bare Jaxpr
            found.append(v)
    return found


def _jaxpr_cost(jaxpr, depth=0):
    """(flops, bytes) for one jaxpr body; recurses into control-flow
    primitives (scan multiplied by its trip count)."""
    flops = 0.0
    nbytes = 0.0
    if depth > 8:           # pathological nesting: stop pricing, stay finite
        return flops, nbytes
    for eqn in jaxpr.eqns:
        f, b = _eqn_cost(eqn, depth)
        flops += f
        nbytes += b
    return flops, nbytes


def _eqn_cost(eqn, depth=0):
    name = eqn.primitive.name
    out_elems = sum(_numel(getattr(ov.aval, "shape", ()))
                    for ov in eqn.outvars)
    io_bytes = float(sum(_aval_bytes(iv.aval) for iv in eqn.invars
                         if hasattr(iv, "aval"))
                     + sum(_aval_bytes(ov.aval) for ov in eqn.outvars))
    inner = _inner_jaxprs(eqn.params)
    if inner:
        trips = float(eqn.params.get("length", 1) or 1)
        f = b = 0.0
        for j in inner:
            jf, jb = _jaxpr_cost(j, depth + 1)
            f += jf
            b += jb
        return f * trips, b * trips
    if name == "dot_general":
        try:
            (lc, _rc), _batch = eqn.params["dimension_numbers"]
            lhs_shape = eqn.invars[0].aval.shape
            k = _numel([lhs_shape[d] for d in lc])
            first_out = _numel(eqn.outvars[0].aval.shape)
            return 2.0 * first_out * k, io_bytes
        except Exception:  # noqa: BLE001 — odd dnums: elementwise floor
            pass
    if name in ("conv_general_dilated",):
        # not emitted by the llama stack; price as heavy elementwise
        return 10.0 * out_elems, io_bytes
    return float(out_elems), io_bytes


class OpCost:
    __slots__ = ("flops", "bytes")

    def __init__(self, flops=0.0, bytes=0.0):
        self.flops = float(flops)
        self.bytes = float(bytes)

    def __repr__(self):
        return f"OpCost(flops={self.flops:.3g}, bytes={self.bytes:.3g})"


class ProgramCost:
    """Aggregate static price of one compiled program, stamped on its
    CompileReport so every dispatch carries predicted-vs-measured cost.
    ``raw_seconds`` is the uncalibrated roofline estimate; callers apply
    a measured calibration scale (platform + overhead) on top."""

    __slots__ = ("name", "flops", "bytes", "raw_seconds", "per_op",
                 "comm_seconds", "exposed_comm_seconds")

    def __init__(self, name, flops, bytes, raw_seconds, per_op,
                 comm_seconds=0.0, exposed_comm_seconds=0.0):
        self.name = name
        self.flops = flops
        self.bytes = bytes
        self.raw_seconds = raw_seconds
        self.per_op = per_op        # [(op name, OpCost)] heaviest-first
        # interconnect traffic of collective-bearing ops (0.0 for the
        # common single-chip program); "exposed" is what overlap credit
        # did not hide — the objective pir/overlap.py minimizes
        self.comm_seconds = float(comm_seconds)
        self.exposed_comm_seconds = float(exposed_comm_seconds)

    def summary(self):
        out = {"name": self.name, "flops": self.flops,
               "bytes": self.bytes, "raw_seconds": self.raw_seconds,
               "top_ops": [(n, c.flops, c.bytes)
                           for n, c in self.per_op[:5]]}
        if self.comm_seconds:
            out["comm_seconds"] = self.comm_seconds
            out["exposed_comm_seconds"] = self.exposed_comm_seconds
        return out

    def __repr__(self):
        return (f"ProgramCost({self.name!r}, {self.flops:.3g} flops, "
                f"{self.bytes:.3g} B, {self.raw_seconds:.3g}s raw)")


class CostModel(DataflowAnalysis):
    """Forward pricing pass: facts map id(op) -> OpCost computed from
    the op's stamped operand/result types (eqn-backed ops price from
    their jaxpr avals, control flow recursively with scan trip counts;
    fused ``pt.*`` ops are priced memory-bound from value byte traffic).
    ``analyze`` folds the facts into a ProgramCost with a roofline time
    estimate t = max(flops / (peak * eff), bytes / hbm_bps)."""

    direction = "forward"
    name = "cost"

    def __init__(self, roofline=None, interconnect=None):
        self.roofline = dict(DEFAULT_ROOFLINE)
        if roofline:
            self.roofline.update(roofline)
        self.interconnect = dict(DEFAULT_INTERCONNECT)
        if interconnect:
            self.interconnect.update(interconnect)

    @staticmethod
    def _value_bytes(values):
        return float(sum(
            _numel(v.shape) * _DTYPE_BYTES.get(str(v.dtype), 4)
            for v in values))

    def _op_cost(self, op: Operation) -> OpCost:
        try:
            if op.eqn is not None:
                f, b = _eqn_cost(op.eqn)
                return OpCost(f, b)
        except Exception:  # noqa: BLE001 — never fail a compile over pricing
            pass
        # fused regions carry their roofline provenance: the members'
        # summed flops (the math still happens — an absorbed dot_general
        # must not look memory-bound to shard_search/overlap) over the
        # fused boundary traffic
        fg = op.attrs.get("fusion_group")
        if isinstance(fg, dict) and "flops" in fg:
            try:
                return OpCost(float(fg["flops"]), float(fg["bytes"]))
            except Exception:  # noqa: BLE001 — malformed attrs: estimate
                pass
        # other fused pt.* op (or unpriceable eqn): memory-bound estimate
        # from the stamped value types; 2 flops/output element keeps the
        # compute axis populated
        out_b = self._value_bytes(op.outputs)
        in_b = self._value_bytes(op.inputs)
        out_elems = sum(_numel(v.shape) for v in op.outputs)
        return OpCost(2.0 * out_elems, in_b + out_b)

    def transfer(self, op: Operation, facts: dict) -> bool:
        if id(op) in facts:
            return False
        facts[id(op)] = self._op_cost(op)
        return True

    def group_bytes_saved(self, members, boundary_inputs, outputs):
        """Predicted HBM bytes a fusion group saves: the unfused members'
        summed operand+result traffic minus the fused op's boundary
        traffic (each boundary input read once, each result written
        once). Positive iff intermediates that used to round-trip HBM
        now die inside the fused kernel — the fuse pass's strict commit
        criterion. Duplicable members are excluded by the caller (their
        traffic persists either way and cancels)."""
        unfused = sum(self._op_cost(op).bytes for op in members)
        fused = (self._value_bytes(boundary_inputs)
                 + self._value_bytes(outputs))
        return unfused - fused

    def epilogue_bytes_saved(self, anchor, members, boundary_inputs,
                             outputs):
        """Predicted HBM bytes an anchored (epilogue) group saves. Same
        strict fused-vs-unfused comparison as ``group_bytes_saved`` but
        the compute anchor (a dot_general or nested fused region) is
        priced by its STAMPED value traffic, not ``_op_cost``: the
        anchor's flops happen either way, its operand reads cancel
        exactly against the fused op's boundary reads (or against an
        in-group producer's saved intermediate), and what fusion
        actually eliminates is the anchor's result write — the matmul
        output that used to round-trip HBM before the epilogue chain
        re-read it — unless that result is promoted to a group output.
        Pricing the anchor through ``_eqn_cost`` instead would let its
        accumulation-traffic estimate leak into the decision and
        overstate the win."""
        chain = [op for op in members if op is not anchor]
        unfused = (sum(self._op_cost(op).bytes for op in chain)
                   + self._value_bytes(anchor.inputs)
                   + self._value_bytes(anchor.outputs))
        fused = (self._value_bytes(boundary_inputs)
                 + self._value_bytes(outputs))
        return unfused - fused

    def analyze(self, prog: Program) -> ProgramCost:
        facts = self.run(prog)
        flops = sum(c.flops for c in facts.values())
        nbytes = sum(c.bytes for c in facts.values())
        eff_flops = self.roofline["peak_flops"] * self.roofline["efficiency"]
        raw = max(flops / eff_flops if eff_flops > 0 else 0.0,
                  nbytes / self.roofline["hbm_bps"]
                  if self.roofline["hbm_bps"] > 0 else 0.0)
        per_op = sorted(
            ((op.name, facts[id(op)]) for op in prog.ops),
            key=lambda nc: -(nc[1].flops + nc[1].bytes))
        comm = exposed = 0.0
        try:
            rep = self.exposed_comm_seconds(prog, facts)
            comm, exposed = rep["comm_seconds"], rep["exposed_seconds"]
        except Exception:  # noqa: BLE001 — pricing may never cost a compile
            pass
        return ProgramCost(prog.name, flops, nbytes, raw, per_op,
                           comm_seconds=comm, exposed_comm_seconds=exposed)

    # -- exposed-communication term (interconnect ledger row) ---------------
    def comm_seconds(self, op: Operation) -> float:
        """Interconnect seconds this op spends moving bytes: every
        collective reachable from its eqn (ops/collectives.py tags),
        priced on the baked ICI ledger row. 0.0 for pure-compute ops."""
        if op.eqn is None:
            return 0.0
        from ..ops.collectives import collective_traffic
        hits = collective_traffic(op.eqn)
        if not hits:
            return 0.0
        bps = self.interconnect["ici_bps"]
        lat = self.interconnect["link_latency_s"]
        return sum(nbytes / bps + lat for _, nbytes in hits if bps > 0)

    def _compute_seconds(self, cost: OpCost) -> float:
        eff = self.roofline["peak_flops"] * self.roofline["efficiency"]
        return max(cost.flops / eff if eff > 0 else 0.0,
                   cost.bytes / self.roofline["hbm_bps"]
                   if self.roofline["hbm_bps"] > 0 else 0.0)

    def exposed_comm_seconds(self, prog: Program, facts=None) -> dict:
        """Schedule-aware communication price of the program as ordered:
        for each collective-bearing op, the compute ops scheduled between
        it and the first consumer of any of its results earn overlap
        credit (async dispatch hides comm under them); what the credit
        does not cover is *exposed*. Windows are credited independently
        (optimistic: interconnect contention between overlapping windows
        is ignored, but other collectives never count as credit)."""
        if facts is None:
            facts = self.run(prog)
        comm_s = [self.comm_seconds(op) for op in prog.ops]
        compute_s = [self._compute_seconds(facts[id(op)])
                     for op in prog.ops]
        first_use = {}
        for i, op in enumerate(prog.ops):
            for v in op.inputs:
                first_use.setdefault(id(v), i)
        total = exposed = 0.0
        n = 0
        for i, op in enumerate(prog.ops):
            if comm_s[i] <= 0.0:
                continue
            n += 1
            total += comm_s[i]
            j = min((first_use.get(id(o), len(prog.ops))
                     for o in op.outputs), default=len(prog.ops))
            credit = sum(compute_s[k] for k in range(i + 1, j)
                         if comm_s[k] <= 0.0)
            exposed += max(0.0, comm_s[i] - credit)
        return {"comm_seconds": total, "exposed_seconds": exposed,
                "collectives": n}


class ShardingConsistency(DataflowAnalysis):
    """Forward propagation of optional ``Value.sharding`` annotations
    over a FlatLattice: an op whose annotated operands agree propagates
    that sharding to unannotated outputs; operands that disagree (and
    shape-preserving ops whose stamped output annotation contradicts the
    propagated one) join to CONFLICT. A join conflict only becomes a
    reported inconsistency once the op's outputs are annotated —
    annotated inputs feeding a not-yet-propagated interior (the window
    between annotate_inputs and the shard_prop pass, which every
    earlier pass's verifier run observes) are pending constraints, not
    an error. ``conflicts`` lists (op, detail) after ``run``. This is deliberately the *consistency* half of GSPMD
    propagation — the sharding-propagation pass (pir/shard_prop.py)
    supplies the decision procedure, then re-runs this to prove its
    assignment. Ops stamped with an ``attrs["sharding_rule"]`` contract
    (a contracting dot, a transpose, a cost-chosen reshard point) are
    their own boundary: operands legitimately carry different shardings
    there and the outputs take exactly their stamped annotation — but a
    declared rule whose outputs are NOT all annotated is itself flagged,
    so a forged or half-applied stamp cannot silence the check."""

    direction = "forward"
    name = "sharding"

    def __init__(self):
        self.lattice = FlatLattice()
        self.conflicts: list[tuple[Operation, str]] = []
        self._flagged: set[int] = set()

    @staticmethod
    def _annot(v: Value):
        return getattr(v, "sharding", None)

    def boundary(self, prog: Program) -> dict:
        facts = {}
        for v in list(prog.inputs) + list(prog.constants):
            facts[id(v)] = self._annot(v)
        return facts

    def transfer(self, op: Operation, facts: dict) -> bool:
        rule = op.attrs.get("sharding_rule") if op.attrs else None
        if rule is not None:
            # declared operand->result contract: no operand join; the
            # stamped output annotations ARE the facts (and must exist)
            if any(self._annot(o) is None for o in op.outputs) \
                    and id(op) not in self._flagged:
                self._flagged.add(id(op))
                self.conflicts.append(
                    (op, f"sharding_rule {rule!r} declared but not every "
                         f"output carries an annotation"))
            changed = False
            for o in op.outputs:
                fact = self._annot(o)
                if facts.get(id(o), None) != fact:
                    facts[id(o)] = fact
                    changed = True
            return changed
        joined = None
        for v in op.inputs:
            fact = self.lattice.join(facts.get(id(v)), self._annot(v))
            joined = self.lattice.join(joined, fact)
        # a join conflict is an ERROR only once this op's outputs carry
        # annotations — i.e. somebody claims propagation committed
        # through here without declaring a sharding_rule. Annotated
        # inputs feeding a not-yet-propagated interior (the state
        # between annotate_inputs and the shard_prop pass) are pending
        # constraints, not an inconsistency.
        committed = any(self._annot(o) is not None for o in op.outputs)
        if joined is CONFLICT and committed and id(op) not in self._flagged:
            self._flagged.add(id(op))
            annots = [(v.vid, facts.get(id(v), self._annot(v)))
                      for v in op.inputs]
            self.conflicts.append(
                (op, f"operands carry irreconcilable shardings: "
                     f"{[(f'%{vid}', s) for vid, s in annots if s]}"))
        changed = False
        for o in op.outputs:
            fact = self.lattice.join(joined, self._annot(o))
            if fact is CONFLICT and joined is not CONFLICT \
                    and id(op) not in self._flagged:
                self._flagged.add(id(op))
                self.conflicts.append(
                    (op, f"output %{o.vid} annotated {self._annot(o)!r} "
                         f"but operands propagate {joined!r}"))
            if facts.get(id(o), None) != fact:
                facts[id(o)] = fact
                changed = True
        return changed
