"""Dataflow analysis framework over pir.Program.

reference: paddle/pir/include/pass/analysis_manager.h (pir analyses
feeding passes) — here a generic join-semilattice worklist engine over
the straight-line SSA op list, so future passes (the ROADMAP's
GSPMD-style sharding propagation, collective-overlap scheduling) are
written as pure transfer functions instead of ad-hoc graph walks.

Three concrete analyses ship with the framework:

* **ShapeDtypeInference** (forward): re-derives every Value's abstract
  type from the program inputs/constants — eqn-backed ops from the
  jaxpr avals they replay, fused ``pt.*`` ops through ``jax.eval_shape``
  of their callable. Backs the verifier's ``type-mismatch`` rule.
* **Liveness** (backward): live-Value sets per program point plus
  use/def indices; feeds ``check_donation_safety`` which statically
  rejects the donated-double-buffer hazard COMPILER.md previously only
  documented (a donated buffer read again after the in-place-style op
  that aliases over it).
* **ShardingConsistency** (forward): propagates optional per-Value
  sharding annotations (``Value.sharding``) and reports conflicts —
  the seed of the sharding-propagation pass: that pass will *choose*
  shardings; this analysis already proves a chosen assignment coherent.

Programs here are topologically-ordered straight-line SSA (no control
flow at this level — scans/whiles are single ops), so the fixpoint
converges in one sweep; the worklist engine still re-enqueues dependents
so transfer functions may be written without ordering assumptions.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Optional

from .ir import Operation, Program, Value

__all__ = ["Lattice", "FlatLattice", "DataflowAnalysis",
           "ShapeDtypeInference", "Liveness", "ShardingConsistency",
           "DonationHazard", "check_donation_safety", "CONFLICT"]


class _Conflict:
    """Lattice top: irreconcilable facts met."""

    def __repr__(self):
        return "<CONFLICT>"


CONFLICT = _Conflict()


class Lattice:
    """Join-semilattice interface: ``bottom`` (no information) joined
    upward toward ``CONFLICT`` (contradictory information)."""

    def bottom(self):
        return None

    def join(self, a, b):  # pragma: no cover - interface
        raise NotImplementedError


class FlatLattice(Lattice):
    """bottom (None) < any concrete fact < CONFLICT. Two distinct
    concrete facts join to CONFLICT — the shape every annotation-
    consistency analysis (sharding, layout, memory space) starts from."""

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if a is CONFLICT or b is CONFLICT:
            return CONFLICT
        return a if a == b else CONFLICT


class DataflowAnalysis:
    """Worklist fixpoint over a Program's op list.

    Subclasses set ``direction`` ("forward" | "backward") and implement
    ``boundary(prog)`` (seed facts) and ``transfer(op, facts)`` which
    updates ``facts`` in place and returns True when anything changed.
    ``run`` returns the fact map after convergence. Facts are keyed by
    ``id(Value)`` (or anything else the subclass chooses — the engine
    only re-enqueues dependent ops on change).
    """

    direction = "forward"
    name = "analysis"

    def boundary(self, prog: Program) -> dict:
        return {}

    def transfer(self, op: Operation, facts: dict) -> bool:
        raise NotImplementedError  # pragma: no cover - interface

    def run(self, prog: Program) -> dict:
        facts = self.boundary(prog)
        forward = self.direction == "forward"
        order = prog.ops if forward else list(reversed(prog.ops))
        # dependents: forward -> ops consuming my outputs; backward ->
        # ops defining my inputs
        users = prog.users()
        dependents: dict[int, list[Operation]] = {}
        for op in prog.ops:
            if forward:
                deps = [u for o in op.outputs for u in users.get(o, ())
                        if u is not None]
            else:
                deps = [v.op for v in op.inputs if v.op is not None]
            dependents[id(op)] = deps
        worklist = deque(order)
        queued = {id(op) for op in order}
        steps = 0
        budget = max(16, len(prog.ops)) * 8    # straight-line: 1 sweep;
        while worklist:                        # budget guards bad transfers
            op = worklist.popleft()
            queued.discard(id(op))
            steps += 1
            if steps > budget:
                raise RuntimeError(
                    f"dataflow analysis {self.name!r} did not converge "
                    f"on {prog.name!r} within {budget} steps")
            if self.transfer(op, facts):
                for dep in dependents[id(op)]:
                    if id(dep) not in queued:
                        worklist.append(dep)
                        queued.add(id(dep))
        return facts


# --------------------------------------------------------------------------
# shape/dtype inference
# --------------------------------------------------------------------------

class ShapeDtypeInference(DataflowAnalysis):
    """facts: id(Value) -> (shape tuple, dtype str). Inputs/constants
    seed from their stamped types (the program boundary is trusted);
    eqn ops derive outputs from the replayed jaxpr's avals; fused ops
    re-derive through jax.eval_shape of the fused callable (cached per
    op). The verifier compares these derived facts against the stamped
    ``Value.shape/dtype`` (rule ``type-mismatch``)."""

    direction = "forward"
    name = "shape_dtype"

    def __init__(self):
        self._fused_cache: dict[int, Optional[list]] = {}

    @staticmethod
    def _key(shape, dtype):
        return (tuple(shape), str(dtype))

    def boundary(self, prog: Program) -> dict:
        facts = {}
        for v in prog.inputs:
            facts[id(v)] = self._key(v.shape, v.dtype)
        for v in prog.constants:
            facts[id(v)] = self._key(v.shape, v.dtype)
        return facts

    def derived_out_types(self, op: Operation, facts: dict):
        """[(shape, dtype_str)] for op's outputs, or None when underived
        (fused op whose abstract eval is unavailable)."""
        if op.eqn is not None:
            return [self._key(tuple(getattr(ov.aval, "shape", ())),
                              getattr(ov.aval, "dtype", None))
                    for ov in op.eqn.outvars]
        cached = self._fused_cache.get(id(op), False)
        if cached is not False:
            return cached
        import jax
        try:
            in_avals = [jax.ShapeDtypeStruct(facts[id(v)][0],
                                             facts[id(v)][1])
                        for v in op.inputs]
            outs = jax.eval_shape(lambda *a: op.evaluate(list(a)), *in_avals)
            derived = [self._key(o.shape, o.dtype) for o in outs]
        except Exception:  # noqa: BLE001 — an un-abstractable fused op
            derived = None  # just opts out of derivation (stays checkable
        self._fused_cache[id(op)] = derived   # structurally, not by type)
        return derived

    def derived_in_types(self, op: Operation):
        """Expected operand types, or None (only eqn ops pin operands)."""
        if op.eqn is None:
            return None
        return [self._key(tuple(getattr(iv.aval, "shape", ())),
                          getattr(iv.aval, "dtype", None))
                for iv in op.eqn.invars]

    def transfer(self, op: Operation, facts: dict) -> bool:
        if any(id(v) not in facts for v in op.inputs):
            return False            # operands not yet derived
        derived = self.derived_out_types(op, facts)
        if derived is None:
            derived = [self._key(o.shape, o.dtype) for o in op.outputs]
        changed = False
        for v, d in zip(op.outputs, derived):
            if facts.get(id(v)) != d:
                facts[id(v)] = d
                changed = True
        return changed


# --------------------------------------------------------------------------
# liveness + donation safety
# --------------------------------------------------------------------------

class Liveness(DataflowAnalysis):
    """Backward liveness. After ``run``, facts map ``("after", i)`` (op
    index) -> frozenset of Value ids live *after* op i executes; the
    boundary ``("after", len(ops)-1)``... is seeded from the program
    outputs. Also exposes ``last_use``/``uses`` index maps (computed in
    run()) for clients that want ranges rather than sets."""

    direction = "backward"
    name = "liveness"

    def __init__(self):
        self.index: dict[int, int] = {}
        self.uses: dict[int, list[int]] = {}       # id(Value) -> op idxs
        self.last_use: dict[int, int] = {}         # id(Value) -> op idx

    def boundary(self, prog: Program) -> dict:
        self.index = {id(op): i for i, op in enumerate(prog.ops)}
        self.uses = {}
        for i, op in enumerate(prog.ops):
            for v in op.inputs:
                self.uses.setdefault(id(v), []).append(i)
        self.last_use = {vid: idxs[-1] for vid, idxs in self.uses.items()}
        out_live = frozenset(id(v) for v in prog.outputs)
        n = len(prog.ops)
        facts = {("after", n - 1): out_live} if n else {}
        facts["exit"] = out_live
        return facts

    def transfer(self, op: Operation, facts: dict) -> bool:
        i = self.index[id(op)]
        live_after = facts.get(("after", i), frozenset())
        live_before = (live_after - {id(o) for o in op.outputs}) \
            | {id(v) for v in op.inputs}
        changed = False
        if facts.get(("before", i)) != live_before:
            facts[("before", i)] = live_before
            changed = True
        if i > 0:
            prev = facts.get(("after", i - 1), frozenset())
            merged = prev | live_before
            if merged != prev:
                facts[("after", i - 1)] = merged
                changed = True
        return changed


# ops that alias an operand's buffer into a same-typed output under
# donation — the "in-place" shapes XLA folds a donated input into. A
# donated Value must be DEAD after the first of these consumes it;
# elementwise reuse (x*2) is not an overwrite and stays unrestricted.
_OVERWRITE_OPS = ("dynamic_update_slice", "dynamic-update-slice",
                  "scatter", "scatter-add", "scatter_add", "scan", "while")


class DonationHazard:
    __slots__ = ("value", "overwrite_op", "overwrite_index", "use_index")

    def __init__(self, value, overwrite_op, overwrite_index, use_index):
        self.value = value
        self.overwrite_op = overwrite_op
        self.overwrite_index = overwrite_index
        self.use_index = use_index

    def __repr__(self):
        return (f"DonationHazard(%{self.value.vid} overwritten by "
                f"{self.overwrite_op.name!r} at op {self.overwrite_index}, "
                f"read again at op {self.use_index})")


def check_donation_safety(prog: Program, donate_argnums) -> list:
    """Statically reject the donated-double-buffer hazard: a donated
    program input consumed by an overwrite-shaped op (its buffer aliased
    into a same-shape/dtype output) and then *read again* later — on
    device the second read would see the overwritten buffer. Returns
    [DonationHazard]; empty = safe. The real serving decode programs
    pass (each donated KV pool feeds exactly its fused scan, last use ==
    overwrite point)."""
    lv = Liveness()
    lv.run(prog)
    hazards = []
    for argnum in donate_argnums or ():
        if argnum >= len(prog.inputs):
            continue
        d = prog.inputs[argnum]
        use_idxs = lv.uses.get(id(d), [])
        if len(use_idxs) < 2:
            continue                    # single consumer: trivially safe
        for i in use_idxs:
            op = prog.ops[i]
            bare = op.name.split(".")[-1]
            if op.name not in _OVERWRITE_OPS and bare not in _OVERWRITE_OPS:
                continue
            dkey = (tuple(d.shape), str(d.dtype))
            if not any((tuple(o.shape), str(o.dtype)) == dkey
                       for o in op.outputs):
                continue
            later = [j for j in use_idxs if j > i]
            if later:
                hazards.append(DonationHazard(d, op, i, later[0]))
                break
    return hazards


# --------------------------------------------------------------------------
# sharding-annotation consistency
# --------------------------------------------------------------------------

class ShardingConsistency(DataflowAnalysis):
    """Forward propagation of optional ``Value.sharding`` annotations
    over a FlatLattice: an op whose annotated operands agree propagates
    that sharding to unannotated outputs; operands that disagree (and
    shape-preserving ops whose stamped output annotation contradicts the
    propagated one) join to CONFLICT. ``conflicts`` lists (op, detail)
    after ``run``. This is deliberately the *consistency* half of GSPMD
    propagation — the future sharding-propagation pass supplies the
    decision procedure, then re-runs this to prove its assignment."""

    direction = "forward"
    name = "sharding"

    def __init__(self):
        self.lattice = FlatLattice()
        self.conflicts: list[tuple[Operation, str]] = []
        self._flagged: set[int] = set()

    @staticmethod
    def _annot(v: Value):
        return getattr(v, "sharding", None)

    def boundary(self, prog: Program) -> dict:
        facts = {}
        for v in list(prog.inputs) + list(prog.constants):
            facts[id(v)] = self._annot(v)
        return facts

    def transfer(self, op: Operation, facts: dict) -> bool:
        joined = None
        for v in op.inputs:
            fact = self.lattice.join(facts.get(id(v)), self._annot(v))
            joined = self.lattice.join(joined, fact)
        if joined is CONFLICT and id(op) not in self._flagged:
            self._flagged.add(id(op))
            annots = [(v.vid, facts.get(id(v), self._annot(v)))
                      for v in op.inputs]
            self.conflicts.append(
                (op, f"operands carry irreconcilable shardings: "
                     f"{[(f'%{vid}', s) for vid, s in annots if s]}"))
        changed = False
        for o in op.outputs:
            fact = self.lattice.join(joined, self._annot(o))
            if fact is CONFLICT and joined is not CONFLICT \
                    and id(op) not in self._flagged:
                self._flagged.add(id(op))
                self.conflicts.append(
                    (op, f"output %{o.vid} annotated {self._annot(o)!r} "
                         f"but operands propagate {joined!r}"))
            if facts.get(id(o), None) != fact:
                facts[id(o)] = fact
                changed = True
        return changed
