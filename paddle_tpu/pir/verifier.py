"""Structural verifier for pir.Program.

reference: paddle/pir/include/core/verify.h (pir::Verify walks every
op's VerifySig/VerifyRegion) — the invariant wall between "a pass has a
bug" and "the bug ships in a compiled artifact". Every check is a named
rule from a CLOSED registry (same discipline as the metric catalog and
fault sites); a failure raises the typed ``IRVerificationError`` naming
the op, the rule, and a printed IR excerpt around the failure point.

Runs under ``FLAGS_pir_verify``:

* ``"on"`` — after capture and after *every* enabled pass (tests and
  tools run here; tier-1 sets it in tests/conftest.py);
* ``"boundary"`` (default) — after capture and after the final pass
  only: production pays two walks per compile, not N;
* ``"off"`` — never.

A verify failure in the compile pipeline degrades to plain ``jax.jit``
counted in ``pir_fallback_total{stage="verify"}`` — the verifier may
reject a program, never break a compile. Wall time lands in
``pir_verify_seconds``; each rejection in
``pir_verify_failures_total{rule}``. ``fault_point("compile.verify")``
is the chaos seam: an injected fault here must degrade identically
(it is wrapped as ``verifier-error``, not allowed to escape).
"""

from __future__ import annotations

import time
from typing import Optional

from .analysis import (ShapeDtypeInference, ShardingConsistency,
                       check_donation_safety)
from .ir import Operation, Program

__all__ = ["RULES", "EFFECT_SCOPES", "IRVerificationError",
           "verify_program", "verify_mode"]

# The closed rule registry. tools/static_check.py and the mutation
# matrix (pir/mutate.py) both key on these names.
RULES = {
    "def-before-use": "every operand is defined (input/constant/earlier "
                      "op output) before the op that consumes it",
    "single-def": "every Value is defined exactly once (SSA)",
    "arity": "operand/result counts match the replayed eqn's signature",
    "dangling-value": "program outputs (and operand back-references) "
                      "resolve to a definition inside the program",
    "dead-code": "post-DCE only: no side-effect-free op whose results "
                 "never reach a program output survives; fused regions "
                 "are held per-result (no dead promoted group output)",
    "effect-order": "stateful paged-KV ops (kv.write / kv.rollback "
                    "scopes) keep their captured program order",
    "type-mismatch": "stamped Value shape/dtype agrees with the "
                     "re-derived abstract eval (jaxpr avals for "
                     "replayed eqns, jax.eval_shape for fused ops)",
    "donation-alias": "a donated input is dead once an overwrite-shaped "
                      "op aliases its buffer (no donated double-buffer)",
    "sharding-conflict": "sharding annotations propagate without "
                         "contradiction (analysis.ShardingConsistency)",
    "verifier-error": "the verifier itself failed (internal bug or an "
                      "injected compile.verify fault); wrapped, counted, "
                      "degrades like any rejection",
}

# named_scope components that mark an op as a stateful paged-KV effect;
# capture stamps matching ops with attrs["effect"] / attrs["effect_seq"]
# (see capture.from_closed_jaxpr) and the effect-order rule holds them
# to captured program order through every pass.
EFFECT_SCOPES = ("kv.write", "kv.rollback")


class IRVerificationError(Exception):
    """A program failed verification: carries the rule name, the
    offending op (when attributable), and an IR excerpt for the log."""

    def __init__(self, rule: str, message: str,
                 op: Optional[Operation] = None,
                 program: Optional[Program] = None):
        assert rule in RULES, f"unregistered verifier rule {rule!r}"
        self.rule = rule
        self.op_name = op.name if op is not None else None
        self.excerpt = _excerpt(program, op) if program is not None else ""
        text = f"[{rule}] {message}"
        if self.op_name:
            text += f" (op {self.op_name!r})"
        if self.excerpt:
            text += "\n" + self.excerpt
        super().__init__(text)


def _excerpt(prog: Program, op: Optional[Operation], context: int = 3) -> str:
    """A window of the printed IR around the failing op (whole header +
    ellipses), so the error is actionable without re-dumping."""
    try:
        lines = prog.to_string(include_attrs=False).splitlines()
        if op is None:
            return "\n".join(lines[:2 * context + 4])
        probe = f'"{op.name}"'
        at = next((i for i, ln in enumerate(lines)
                   if probe in ln and
                   ln.strip().startswith(", ".join(
                       repr(o) for o in op.outputs)[:8] or '"')), None)
        if at is None:
            at = next((i for i, ln in enumerate(lines) if probe in ln), 0)
        lo, hi = max(1, at - context), min(len(lines) - 1, at + context + 1)
        body = ["  ..."] if lo > 1 else []
        body += lines[lo:hi]
        if hi < len(lines) - 1:
            body.append("  ...")
        return "\n".join([lines[0]] + body + [lines[-1]])
    except Exception:  # noqa: BLE001 — excerpting never masks the failure
        return ""


def verify_mode() -> str:
    """FLAGS_pir_verify, validated: off | boundary | on."""
    from ..framework import flags as _flags
    mode = str(_flags.flag_value("pir_verify")).strip().lower()
    if mode not in ("off", "boundary", "on"):
        raise ValueError(f"FLAGS_pir_verify={mode!r}; "
                         "expected off | boundary | on")
    return mode


def verify_program(prog: Program, *, strict_dead: bool = False,
                   donate_argnums=None, where: str = "capture") -> None:
    """Run every structural rule; raises IRVerificationError on the
    first violation. ``strict_dead`` enables the dead-code rule (only
    meaningful right after a DCE run — before it, dead ops are merely
    unoptimized, not malformed). ``donate_argnums`` (flat input indices)
    enables the donation-alias rule. ``where`` labels the verify point
    (capture / pass name) in errors and metrics exemplars."""
    t0 = time.perf_counter()
    try:
        from ..resilience.faults import fault_point
        fault_point("compile.verify", program=prog.name, where=where)
        _verify(prog, strict_dead=strict_dead, donate_argnums=donate_argnums,
                where=where)
    except IRVerificationError as e:
        _count_failure(e.rule)
        raise
    except Exception as e:  # noqa: BLE001 — internal bug or injected fault:
        # wrap to the typed error so the pipeline degrades (never escapes)
        _count_failure("verifier-error")
        raise IRVerificationError(
            "verifier-error",
            f"verify({where}) of {prog.name!r} failed internally: "
            f"{type(e).__name__}: {e}") from e
    finally:
        try:
            from ..observability.catalog import metric
            metric("pir_verify_seconds").observe(time.perf_counter() - t0)
        except Exception:  # noqa: BLE001 — timing never breaks a verify
            pass


def _count_failure(rule: str) -> None:
    try:
        from ..observability.catalog import metric
        metric("pir_verify_failures_total", rule=rule).inc()
    except Exception:  # noqa: BLE001
        pass


def _verify(prog, *, strict_dead, donate_argnums, where):
    defined: dict[int, str] = {}
    for v in prog.inputs:
        defined[id(v)] = "input"
    for v in prog.constants:
        defined[id(v)] = "const"
    op_ids = {id(op) for op in prog.ops}

    effect_prev = None        # (seq, op) of the last effect op seen
    for op in prog.ops:
        # -- def-before-use ------------------------------------------------
        for v in op.inputs:
            if id(v) not in defined:
                raise IRVerificationError(
                    "def-before-use",
                    f"operand %{v.vid} of {op.name!r} is used before any "
                    f"definition (no input, constant, or earlier op "
                    f"defines it)", op=op, program=prog)
        # -- single-def ----------------------------------------------------
        for o in op.outputs:
            if id(o) in defined:
                raise IRVerificationError(
                    "single-def",
                    f"%{o.vid} is defined again by {op.name!r} (already "
                    f"defined as {defined[id(o)]})", op=op, program=prog)
            defined[id(o)] = f"op:{op.name}"
        # -- dangling-value (operand back-reference) ------------------------
        for v in op.inputs:
            if v.op is not None and defined.get(id(v), "").startswith("op:") \
                    and id(v.op) not in op_ids:
                raise IRVerificationError(
                    "dangling-value",
                    f"operand %{v.vid} of {op.name!r} back-references a "
                    f"defining op not present in the program",
                    op=op, program=prog)
        # -- arity ---------------------------------------------------------
        if op.eqn is not None:
            if len(op.inputs) != len(op.eqn.invars) \
                    or len(op.outputs) != len(op.eqn.outvars):
                raise IRVerificationError(
                    "arity",
                    f"{op.name!r} carries {len(op.inputs)} operands / "
                    f"{len(op.outputs)} results but its eqn expects "
                    f"{len(op.eqn.invars)} / {len(op.eqn.outvars)}",
                    op=op, program=prog)
        elif not op.outputs:
            raise IRVerificationError(
                "arity", f"fused op {op.name!r} produces no results",
                op=op, program=prog)
        # -- effect-order ----------------------------------------------------
        eff = op.attrs.get("effect")
        if eff is not None:
            seq = op.attrs.get("effect_seq")
            if effect_prev is not None and (seq is None
                                            or seq <= effect_prev[0]):
                raise IRVerificationError(
                    "effect-order",
                    f"stateful op {op.name!r} ({eff}, seq={seq}) appears "
                    f"after {effect_prev[1].name!r} "
                    f"(seq={effect_prev[0]}): paged-KV effects must keep "
                    f"captured program order", op=op, program=prog)
            effect_prev = (seq, op)

    # -- dangling-value (program outputs) ----------------------------------
    for v in prog.outputs:
        if id(v) not in defined:
            raise IRVerificationError(
                "dangling-value",
                f"program output %{v.vid} has no definition in the "
                f"program", program=prog)

    # -- type-mismatch ------------------------------------------------------
    inf = ShapeDtypeInference()
    facts = inf.run(prog)
    for op in prog.ops:
        expected_in = inf.derived_in_types(op)
        if expected_in is not None:
            for v, exp in zip(op.inputs, expected_in):
                if (tuple(v.shape), str(v.dtype)) != exp:
                    raise IRVerificationError(
                        "type-mismatch",
                        f"operand %{v.vid} of {op.name!r} is stamped "
                        f"{v.type_str} but the replayed eqn expects "
                        f"{exp[1]}[{','.join(map(str, exp[0]))}]",
                        op=op, program=prog)
        for o in op.outputs:
            derived = facts.get(id(o))
            if derived is not None \
                    and (tuple(o.shape), str(o.dtype)) != derived:
                raise IRVerificationError(
                    "type-mismatch",
                    f"result %{o.vid} of {op.name!r} is stamped "
                    f"{o.type_str} but abstract eval derives "
                    f"{derived[1]}[{','.join(map(str, derived[0]))}]",
                    op=op, program=prog)

    # -- dead-code (strict, post-DCE) ---------------------------------------
    if strict_dead:
        live = set(id(v) for v in prog.outputs)
        for op in reversed(prog.ops):
            if op.has_effects() or op.attrs.get("effect") is not None \
                    or any(id(o) in live for o in op.outputs):
                live.update(id(v) for v in op.inputs)
        for op in prog.ops:
            if not op.has_effects() and op.attrs.get("effect") is None \
                    and not any(id(o) in live for o in op.outputs):
                raise IRVerificationError(
                    "dead-code",
                    f"{op.name!r} survives DCE but none of its results "
                    f"reach a program output", op=op, program=prog)
            # multi-result fused regions are held to PER-RESULT
            # liveness: a region carrying a dead promoted output means
            # DCE failed to shrink its signature — the dead write would
            # silently undo the fusion win the group committed on
            if op.name == "pt.fused_region" \
                    and not op.has_effects() \
                    and op.attrs.get("effect") is None:
                for o in op.outputs:
                    if id(o) not in live:
                        raise IRVerificationError(
                            "dead-code",
                            f"fused region result %{o.vid} survives DCE "
                            f"but never reaches a program output "
                            f"(dead promoted group output)",
                            op=op, program=prog)

    # -- donation-alias -----------------------------------------------------
    if donate_argnums:
        hazards = check_donation_safety(prog, donate_argnums)
        if hazards:
            h = hazards[0]
            raise IRVerificationError(
                "donation-alias",
                f"donated input %{h.value.vid} is read again (op "
                f"{h.use_index}) after {h.overwrite_op.name!r} (op "
                f"{h.overwrite_index}) aliases its buffer into a "
                f"same-typed result — donated double-buffer hazard",
                op=h.overwrite_op, program=prog)

    # -- sharding-conflict ---------------------------------------------------
    if any(getattr(v, "sharding", None) is not None
           for op in prog.ops for v in list(op.inputs) + list(op.outputs)) \
            or any(getattr(v, "sharding", None) is not None
                   for v in prog.inputs):
        sc = ShardingConsistency()
        sc.run(prog)
        if sc.conflicts:
            op, detail = sc.conflicts[0]
            raise IRVerificationError(
                "sharding-conflict", detail, op=op, program=prog)
