"""Collective-overlap scheduling pass: hide communication under compute.

reference: Tile-Level Activation Overlap (arXiv:2607.02521) and the
overlap half of Operator Fusion in XLA (arXiv:2301.13062) — at PIR
granularity rather than tile granularity: collective-bearing ops
(ops/collectives.py tags; a ``shard_map`` wrapping a psum counts) are
hoisted to the earliest position their operands allow, which widens
the window between a collective's issue and the first consumer of its
result. Independent compute in that window earns overlap credit in the
CostModel's exposed-communication term; the pass commits a reorder
ONLY if that term strictly decreases, otherwise it restores the
captured order and reports zero edits — scheduling may never regress
the score it optimizes.

Legality: an op moves only earlier, to a slot after the defs of all
its operands; effectful ops are immovable AND act as barriers (nothing
hoists across them), so the verifier's effect-order rule is preserved
by construction. Pure-op reorder is semantics-free in SSA replay.
"""

from __future__ import annotations

from .analysis import CostModel
from .ir import Program
from .passes import Pass, PassResult

__all__ = ["CollectiveOverlap"]

# relative improvements smaller than this are noise, not a schedule win
_MIN_GAIN = 1e-12


class CollectiveOverlap(Pass):
    name = "overlap"

    def __init__(self, cost_model=None):
        self.cost = cost_model or CostModel()

    def run(self, prog: Program) -> PassResult:
        comm_idx = [i for i, op in enumerate(prog.ops)
                    if self.cost.comm_seconds(op) > 0.0]
        if not comm_idx:
            return PassResult(0, "no-collectives")
        before = self.cost.exposed_comm_seconds(prog)["exposed_seconds"]
        original = list(prog.ops)
        moves = self._hoist(prog)
        if not moves:
            return PassResult(0, f"exposed={before:.3g}s moves=0")
        after = self.cost.exposed_comm_seconds(prog)["exposed_seconds"]
        if after >= before - _MIN_GAIN * max(1.0, before):
            prog.ops = original     # no strict win: keep captured order
            return PassResult(0, f"exposed={before:.3g}s moves=0 "
                                 f"(reorder not profitable)")
        try:
            from ..observability.catalog import metric as _metric
            _metric("pir_exposed_comm_seconds",
                    program=prog.name).set(after)
        except Exception:  # noqa: BLE001 — metrics never cost a compile
            pass
        return PassResult(
            moves, f"exposed {before:.3g}s -> {after:.3g}s moves={moves}")

    def _hoist(self, prog: Program) -> int:
        """Move each collective-bearing pure op to the earliest legal
        index: after every operand's def and after the last preceding
        barrier (effectful op). Single left-to-right sweep; removing an
        op and reinserting it earlier preserves every other relative
        order, so SSA dominance cannot break."""
        moves = 0
        i = 0
        while i < len(prog.ops):
            op = prog.ops[i]
            if self.cost.comm_seconds(op) <= 0.0 or op.has_effects() \
                    or (op.attrs and op.attrs.get("effect")):
                i += 1
                continue
            deps = {id(v) for v in op.inputs}
            earliest = 0
            for j in range(i - 1, -1, -1):
                prev = prog.ops[j]
                if prev.has_effects() or (prev.attrs
                                          and prev.attrs.get("effect")) \
                        or any(id(o) in deps for o in prev.outputs):
                    earliest = j + 1
                    break
            if earliest < i:
                prog.ops.pop(i)
                prog.ops.insert(earliest, op)
                moves += 1
            i += 1
        return moves
