"""Persistent compile cache: on-disk serialized programs.

reference capability: the reference's PIR serialize/deserialize
(paddle/fluid/pir/serialize_deserialize/) + inference program caching.
TPU-native design: the artifact is a serialized ``jax.export.Exported``
(StableHLO) of the post-pass program — warm starts skip the pass
pipeline's output re-lowering and XLA compilation entirely (round 5
showed ≥700M configs historically dying at exactly that step).

Contract (RESILIENCE.md discipline):

* artifacts are sha256-verified on read; any mismatch / truncation /
  bad magic raises the TYPED ``CompileCacheCorruptionError`` and the
  pipeline falls back to a fresh compile, counting
  ``compile_cache_corrupt_total`` — corruption can never produce a
  wrong program, only a slower start;
* writes are atomic (tmp + os.replace) and size-cap LRU-evicted
  (``FLAGS_compile_cache_max_bytes``, oldest-read first);
* ``compile.cache_read`` / ``compile.cache_write`` are registered
  fault sites, drilled by tools/chaos_drill.py with the zero-escape
  guarantee.

Layout: ``<dir>/<key>.pirc`` =
``b"PIRC" + u32 header_len + header_json + payload`` where the header
records the payload sha256 and provenance metadata.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading

__all__ = ["CompileCache", "CompileCacheCorruptionError", "default_cache",
           "cache_key", "stats_snapshot"]

_MAGIC = b"PIRC"
_SUFFIX = ".pirc"

# process-local counters, independent of the observability layer so
# bench.py can report hit/miss even with metrics disabled
_STATS = {"hit": 0, "miss": 0, "write": 0, "corrupt": 0, "evict": 0,
          "read_error": 0, "write_error": 0}
_STATS_LOCK = threading.Lock()


def _bump(k, v=1):
    with _STATS_LOCK:
        _STATS[k] += v


def stats_snapshot() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


class CompileCacheCorruptionError(RuntimeError):
    """A cached compile artifact failed verification (bad magic, short
    file, or payload sha256 mismatch). Names the offending file."""


def _metric(name, **labels):
    try:
        from ..observability.catalog import metric
        return metric(name, **labels)
    except Exception:  # noqa: BLE001 — cache never fails over metrics
        class _Nop:
            def inc(self, v=1):
                pass

            def set(self, v):
                pass
        return _Nop()


def cache_key(canonical_hash: str, *, sharding: str = "replicated",
              extra: dict = None) -> str:
    """Artifact key: (canonical IR hash, mesh/sharding spec, dtype/flag
    environment, jax version, backend platform, pipeline version) —
    everything that changes the compiled executable. Sharding-aware by
    construction (GSPMD, arxiv 2105.04663: partitioning decisions are
    part of the program identity)."""
    import jax

    from ..framework import flags as _flags
    from .passes import PIPELINE_VERSION

    def flag(k):
        # some flags register lazily on their module's import (e.g.
        # attention_router); unregistered reads key as None
        try:
            return _flags.flag_value(k)
        except KeyError:
            return None

    env = {
        "ir": canonical_hash,
        "sharding": sharding,
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "pipeline": PIPELINE_VERSION,
        "flags": {k: flag(k) for k in (
            "matmul_precision", "use_bfloat16_matmul",
            "flash_attention_backend", "attention_router", "pir_passes")},
    }
    if extra:
        env["extra"] = {k: str(v) for k, v in sorted(extra.items())}
    text = json.dumps(env, sort_keys=True, default=str)
    return hashlib.sha256(text.encode()).hexdigest()


class CompileCache:
    def __init__(self, directory: str, max_bytes: int = 1 << 28):
        self.dir = directory
        self.max_bytes = int(max_bytes)
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key + _SUFFIX)

    # -- read ---------------------------------------------------------------
    def get(self, key: str):
        """Return (payload_bytes, meta_dict) or None on miss. Raises
        CompileCacheCorruptionError on a failed verification, OSError-
        family on IO trouble (callers treat both as recompile)."""
        from ..resilience.faults import fault_point
        path = self._path(key)
        if not os.path.exists(path):
            return None
        fault_point("compile.cache_read", path=path)
        with open(path, "rb") as f:
            blob = f.read()
        if len(blob) < 8 or blob[:4] != _MAGIC:
            raise CompileCacheCorruptionError(
                f"compile-cache artifact {path} has a bad header "
                "(magic mismatch)")
        (hlen,) = struct.unpack("<I", blob[4:8])
        if len(blob) < 8 + hlen:
            raise CompileCacheCorruptionError(
                f"compile-cache artifact {path} is truncated")
        try:
            header = json.loads(blob[8:8 + hlen].decode())
        except Exception as e:
            raise CompileCacheCorruptionError(
                f"compile-cache artifact {path} has an unreadable "
                f"header: {e}") from None
        payload = blob[8 + hlen:]
        digest = hashlib.sha256(payload).hexdigest()
        if digest != header.get("sha256"):
            raise CompileCacheCorruptionError(
                f"compile-cache artifact {path} failed sha256 "
                f"verification (have {digest[:12]}, "
                f"recorded {str(header.get('sha256'))[:12]})")
        os.utime(path, None)          # LRU recency = last verified read
        return payload, header.get("meta", {})

    # -- write --------------------------------------------------------------
    def put(self, key: str, payload: bytes, meta: dict = None):
        from ..resilience.faults import fault_point
        path = self._path(key)
        header = json.dumps({
            "sha256": hashlib.sha256(payload).hexdigest(),
            "meta": meta or {},
        }).encode()
        tmp = path + f".tmp.{os.getpid()}"
        fault_point("compile.cache_write", path=path)
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<I", len(header)))
            f.write(header)
            f.write(payload)
        os.replace(tmp, path)
        self._evict()

    def drop(self, key: str):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    # -- eviction -----------------------------------------------------------
    def entries(self):
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if not n.endswith(_SUFFIX):
                continue
            p = os.path.join(self.dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_mtime, st.st_size))
        return out

    def total_bytes(self) -> int:
        return sum(sz for _, _, sz in self.entries())

    def _evict(self):
        """Size-capped LRU: drop least-recently-read artifacts until the
        directory fits max_bytes."""
        ents = self.entries()
        total = sum(sz for _, _, sz in ents)
        _metric("compile_cache_bytes").set(total)
        if total <= self.max_bytes:
            return
        evicted = 0
        for p, _, sz in sorted(ents, key=lambda e: e[1]):
            if total <= self.max_bytes:
                break
            try:
                os.unlink(p)
            except OSError:
                continue
            total -= sz
            evicted += 1
        if evicted:
            _bump("evict", evicted)
            _metric("compile_cache_evict_total").inc(evicted)
            _metric("compile_cache_bytes").set(total)


def default_cache():
    """CompileCache from FLAGS_compile_cache_dir ('' = disabled)."""
    from ..framework import flags as _flags
    d = _flags.flag_value("compile_cache_dir")
    if not d:
        return None
    return CompileCache(d, _flags.flag_value("compile_cache_max_bytes"))
