"""PIR-lite program IR: Value / Operation / Program.

reference: paddle/pir/include/core/ (Operation/Value/Block SSA IR) and
paddle/fluid/pir/ — the reference's layer between program capture and
the backend compiler, where pattern rewriting (DRR), DCE/CSE and the
compile cache key all live.

TPU-native design: the captured program already exists as a jaxpr, so
the IR is a THIN, mutable SSA view over it — each Operation either
wraps one ``JaxprEqn`` (replayed verbatim through ``primitive.bind``)
or is a *fused* op carrying a Python callable installed by a rewrite
pattern. That keeps the evaluator trivially faithful (non-rewritten
ops execute byte-for-byte what jax traced) while making the program a
first-class object we can print, hash, transform and key a persistent
compile cache on — the capability COVERAGE.md row 12 previously mapped
wholesale onto "jaxpr/StableHLO" and never exercised.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Callable, Optional

__all__ = ["Value", "Operation", "Program", "canonical_attr_text"]

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _scrub(text: str) -> str:
    """Make repr-derived text process-stable (drop heap addresses)."""
    return _ADDR_RE.sub("0x", text)


def canonical_attr_text(v) -> str:
    """Deterministic, process-stable rendering of an op attribute /
    eqn param — the piece of the canonical hash that must not pick up
    object identities. Nested jaxprs render via jax's printer (stable
    alphabetic var names) with addresses scrubbed; arrays render as a
    content digest; callables by name only."""
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return repr(v)
    if isinstance(v, np.dtype):
        return f"dtype({v.name})"
    if isinstance(v, type):
        return f"type({v.__module__}.{v.__name__})"
    if isinstance(v, dict):
        items = ", ".join(f"{canonical_attr_text(k)}: {canonical_attr_text(x)}"
                          for k, x in sorted(v.items(), key=lambda kv: repr(kv[0])))
        return "{" + items + "}"
    if isinstance(v, (tuple, list, set, frozenset)):
        body = ", ".join(canonical_attr_text(x) for x in v)
        open_, close = ("(", ")") if isinstance(v, tuple) else ("[", "]")
        if isinstance(v, (set, frozenset)):
            open_, close = "{", "}"
        return open_ + body + close
    if hasattr(v, "jaxpr") or type(v).__name__ in ("Jaxpr", "ClosedJaxpr"):
        return "jaxpr<" + _scrub(str(v)) + ">"
    if hasattr(v, "shape") and hasattr(v, "dtype"):  # ndarray-like
        arr = np.asarray(v)
        digest = hashlib.sha256(arr.tobytes()).hexdigest()[:16]
        return f"ndarray({arr.dtype}, {tuple(arr.shape)}, {digest})"
    if callable(v):
        return f"fn<{getattr(v, '__name__', type(v).__name__)}>"
    return _scrub(repr(v))


def _constrained(v, x):
    """Replay hook for annotated values: re-assert ``v.sharding``
    through with_sharding_constraint while a shard_prop mesh scope is
    active (no scope / any failure -> x unchanged). Lazy import: only
    programs the propagation pass annotated ever reach this."""
    from .shard_prop import apply_constraint
    return apply_constraint(x, v.sharding)


class Value:
    """One SSA value: produced by exactly one Operation (or a program
    input / constant), consumed by any number. ``sharding`` is an
    optional annotation (mesh-axes spec) consumed by the sharding
    consistency analysis (pir/analysis.py) — None everywhere until a
    sharding-propagation pass stamps it; it does not participate in
    canonical hashing."""

    __slots__ = ("vid", "shape", "dtype", "op", "sharding")

    def __init__(self, vid: int, shape, dtype, op: Optional["Operation"] = None):
        self.vid = vid
        self.shape = tuple(shape)
        self.dtype = dtype
        self.op = op          # defining op; None for inputs / constants
        self.sharding = None  # optional sharding annotation

    @property
    def type_str(self) -> str:
        return f"{self.dtype}[{','.join(str(s) for s in self.shape)}]"

    @property
    def sharding_str(self) -> str:
        """Printable sharding suffix (``<dp,*>`` style; empty when
        unannotated). Display only — NEVER part of canonical_text:
        identical programs must hash identically whether or not the
        propagation pass annotated them."""
        if self.sharding is None:
            return ""
        return ("<" + ",".join("*" if a is None else str(a)
                               for a in self.sharding) + ">")

    def __repr__(self):
        return f"%{self.vid}: {self.type_str}{self.sharding_str}"


class Operation:
    """One op. Either a replayed jaxpr eqn (``eqn`` set, executed via
    ``eqn.primitive.bind``) or a fused op (``fn`` set, a Python callable
    installed by a rewrite pattern; name prefixed ``pt.``)."""

    __slots__ = ("name", "inputs", "outputs", "attrs", "eqn", "fn", "_canon")

    def __init__(self, name: str, inputs: list, outputs: list,
                 attrs: Optional[dict] = None, eqn=None,
                 fn: Optional[Callable] = None):
        self.name = name
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.attrs = dict(attrs or {})
        self.eqn = eqn
        self.fn = fn
        self._canon = None
        for o in self.outputs:
            o.op = self

    def has_effects(self) -> bool:
        return self.eqn is not None and bool(getattr(self.eqn, "effects", ()))

    def attr_text(self) -> str:
        if self._canon is None:
            params = self.eqn.params if self.eqn is not None else self.attrs
            self._canon = canonical_attr_text(params)
        return self._canon

    def evaluate(self, in_vals: list) -> list:
        """Execute this op on concrete or traced arrays. Replayed eqns
        rebind exactly the way jax.core.eval_jaxpr does — through
        get_bind_params, so call-like primitives (pjit, custom_jvp/vjp,
        scan, ...) reconstruct their callable sub-terms."""
        if self.fn is not None:
            out = self.fn(*in_vals)
            return list(out) if isinstance(out, (tuple, list)) else [out]
        prim = self.eqn.primitive
        subfuns, bind_params = prim.get_bind_params(self.eqn.params)
        out = prim.bind(*subfuns, *in_vals, **bind_params)
        return list(out) if prim.multiple_results else [out]

    def __repr__(self):
        outs = ", ".join(repr(o) for o in self.outputs)
        ins = ", ".join(f"%{v.vid}" for v in self.inputs)
        return f"{outs} = {self.name}({ins})"


class Program:
    """A captured program: inputs -> ops (topological) -> outputs, plus
    bound constants (jaxpr consts and inlined literals)."""

    def __init__(self, name: str = "program"):
        self.name = name
        self.inputs: list[Value] = []
        self.ops: list[Operation] = []
        self.outputs: list[Value] = []
        self.constants: dict[Value, Any] = {}   # Value -> array
        self._next_vid = 0

    # -- construction -------------------------------------------------------
    def new_value(self, shape, dtype, op=None) -> Value:
        v = Value(self._next_vid, shape, dtype, op)
        self._next_vid += 1
        return v

    def add_constant(self, arr) -> Value:
        import numpy as np
        a = np.asarray(arr) if not hasattr(arr, "dtype") else arr
        v = self.new_value(getattr(a, "shape", ()), a.dtype)
        self.constants[v] = arr
        return v

    # -- queries ------------------------------------------------------------
    def users(self) -> dict:
        """Value -> [Operation] consumer map (outputs count as users via
        the None sentinel)."""
        u: dict[Value, list] = {}
        for op in self.ops:
            for v in op.inputs:
                u.setdefault(v, []).append(op)
        for v in self.outputs:
            u.setdefault(v, []).append(None)
        return u

    def num_ops(self) -> int:
        return len(self.ops)

    # -- mutation (rewrites) ------------------------------------------------
    def replace_region(self, region_ops: list, new_op: Operation):
        """Replace a connected set of ops with one fused op. The fused
        op must produce the exact Value objects the region produced (so
        downstream users need no rewiring) and consume only values
        defined outside the region."""
        region = set(map(id, region_ops))
        idx = max(i for i, op in enumerate(self.ops) if id(op) in region)
        # splice the fused op where the last region op sat
        out = []
        for i, op in enumerate(self.ops):
            if id(op) not in region:
                out.append(op)
            elif i == idx:
                out.append(new_op)
        self.ops = out

    # -- execution ----------------------------------------------------------
    def bind(self, *args):
        """Evaluate the program on arrays (concrete or tracers) — the
        faithful interpreter: replayed eqns go through primitive.bind,
        fused ops through their callables. jit-ing this function yields
        the post-rewrite XLA program."""
        if len(args) != len(self.inputs):
            raise TypeError(f"{self.name}: expected {len(self.inputs)} "
                            f"args, got {len(args)}")
        env: dict[int, Any] = {}
        for v, a in zip(self.inputs, args):
            env[id(v)] = a if v.sharding is None else _constrained(v, a)
        for v, c in self.constants.items():
            env[id(v)] = c
        for op in self.ops:
            in_vals = [env[id(v)] for v in op.inputs]
            for v, o in zip(op.outputs, op.evaluate(in_vals)):
                env[id(v)] = o if v.sharding is None else _constrained(v, o)
        return tuple(env[id(v)] for v in self.outputs)

    # -- printing / hashing -------------------------------------------------
    def to_string(self, include_attrs: bool = True, max_ops: int = 0) -> str:
        """Paddle-parity IR dump (reference: pir Program::Print /
        static Program.__str__): one op per line, SSA-numbered."""
        lines = [f"program @{self.name} ("
                 + ", ".join(repr(v) for v in self.inputs) + ") {"]
        for v in self.constants:
            lines.append(f"  %{v.vid} = const : {v.type_str}")
        shown = self.ops if not max_ops else self.ops[:max_ops]
        for op in shown:
            outs = ", ".join(repr(o) for o in op.outputs)
            ins = ", ".join(f"%{v.vid}" for v in op.inputs)
            attr = ""
            if include_attrs:
                params = (op.eqn.params if op.eqn is not None else op.attrs)
                shown_attrs = {k: v for k, v in params.items()
                               if not hasattr(v, "jaxpr")
                               and not callable(v)} if params else {}
                if shown_attrs:
                    attr = " {" + ", ".join(
                        f"{k}={canonical_attr_text(v)}"
                        for k, v in sorted(shown_attrs.items())) + "}"
            lines.append(f"  {outs} = \"{op.name}\"({ins}){attr}")
        if max_ops and len(self.ops) > max_ops:
            lines.append(f"  ... ({len(self.ops) - max_ops} more ops)")
        lines.append("  return " + ", ".join(f"%{v.vid}" for v in self.outputs))
        lines.append("}")
        return "\n".join(lines)

    __str__ = to_string
    __repr__ = lambda self: (f"<pir.Program @{self.name}: "
                             f"{len(self.ops)} ops, "
                             f"{len(self.inputs)} inputs>")

    def canonical_text(self) -> str:
        """Stable renumbered rendering used for hashing: value ids are
        assigned by first use order, constants render as content
        digests, attrs via canonical_attr_text — identical programs
        captured in different processes produce identical text."""
        renum: dict[int, int] = {}

        def rn(v: Value) -> str:
            n = renum.setdefault(id(v), len(renum))
            return f"%{n}:{v.type_str}"

        lines = ["in " + ", ".join(rn(v) for v in self.inputs)]
        for v, c in self.constants.items():
            lines.append(f"{rn(v)} = const {canonical_attr_text(c)}")
        for op in self.ops:
            ins = ", ".join(rn(v) for v in op.inputs)
            outs = ", ".join(rn(v) for v in op.outputs)
            lines.append(f"{outs} = {op.name}({ins}) {op.attr_text()}")
        lines.append("out " + ", ".join(rn(v) for v in self.outputs))
        return "\n".join(lines)

    def canonical_hash(self) -> str:
        return hashlib.sha256(self.canonical_text().encode()).hexdigest()
