"""Capture: lower a traced jaxpr into a pir.Program.

reference: the reference builds PIR programs from Python AST / bytecode
capture (35k LoC); here the imperative API already runs on jax, so
capture is one ``jax.make_jaxpr`` trace followed by a structural
lowering — every eqn becomes one Operation that keeps a reference to
the original ``JaxprEqn`` for faithful replay.
"""

from __future__ import annotations

from typing import Callable

import jax

from .ir import Operation, Program

__all__ = ["from_closed_jaxpr", "capture"]


def _aval_of(var):
    av = var.aval
    return tuple(getattr(av, "shape", ())), getattr(av, "dtype", None)


def _effect_scope(eqn):
    """The paged-KV effect scope this eqn was traced under, or None.
    ops/paged_attention.py wraps its cache-mutating entry points in
    ``jax.named_scope("kv.write" | "kv.rollback")``; the scope survives
    tracing in ``eqn.source_info.name_stack`` and marks the lowered op
    as stateful for the verifier's effect-order rule."""
    from .verifier import EFFECT_SCOPES
    try:
        ns = eqn.source_info.name_stack
        if not getattr(ns, "stack", None):
            return None
        for part in str(ns).split("/"):
            if part in EFFECT_SCOPES:
                return part
    except Exception:  # noqa: BLE001 — scope detection is best-effort
        return None
    return None


def from_closed_jaxpr(closed, name: str = "program") -> Program:
    """Lower a ClosedJaxpr to a Program. Literals become constants so
    every operand is a first-class Value."""
    from jax._src.core import DropVar, Literal

    jaxpr = closed.jaxpr
    prog = Program(name)
    env: dict[int, object] = {}   # id(jax Var) -> Value

    def bind_var(var):
        shape, dtype = _aval_of(var)
        v = prog.new_value(shape, dtype)
        env[id(var)] = v
        return v

    prog.inputs = [bind_var(v) for v in jaxpr.invars]
    for var, const in zip(jaxpr.constvars, closed.consts):
        shape, dtype = _aval_of(var)
        v = prog.new_value(shape, dtype)
        prog.constants[v] = const
        env[id(var)] = v

    def read(var):
        if isinstance(var, Literal):
            return prog.add_constant(var.val)
        return env[id(var)]

    eff_seq = 0
    for eqn in jaxpr.eqns:
        ins = [read(v) for v in eqn.invars]
        outs = []
        for ov in eqn.outvars:
            shape, dtype = _aval_of(ov)
            val = prog.new_value(shape, dtype)
            outs.append(val)
            if not isinstance(ov, DropVar):
                env[id(ov)] = val
        op = Operation(eqn.primitive.name, ins, outs, eqn=eqn)
        scope = _effect_scope(eqn)
        if scope is not None:
            # stateful paged-KV op: stamp the captured program order so
            # the verifier's effect-order rule can hold every pass to it.
            # attrs on eqn-backed ops stay out of attr_text()/canonical
            # hashing — the stamp never perturbs compile-cache keys.
            op.attrs["effect"] = scope
            op.attrs["effect_seq"] = eff_seq
            eff_seq += 1
        prog.ops.append(op)

    prog.outputs = [read(v) for v in jaxpr.outvars]
    return prog


def capture(fn: Callable, *example_args, name: str = None):
    """Trace ``fn`` (positional array args, array or flat-tuple output)
    and lower it. Returns (Program, out_shape_pytree). This is the
    entry the pipeline and tools/ir_dump.py use; jit.to_static builds
    its flat function and calls it too."""
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*example_args)
    prog = from_closed_jaxpr(closed,
                             name or getattr(fn, "__name__", "program"))
    try:
        from ..observability.catalog import metric
        metric("pir_captures_total").inc()
    except Exception:  # noqa: BLE001 — capture never fails over metrics
        pass
    return prog, out_shape
