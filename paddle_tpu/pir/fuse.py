"""CINN-lite auto-fusion v2: cost-guided producer-consumer fusion with
multi-output groups and dot_general epilogue absorption.

reference: paddle/cinn/ — the reference stack's fifth layer turns PIR
subgraphs into fused kernels. The v1 pass (PR 16) grouped
single-output elementwise/layout/reduce chains; v2 closes the two
known limitations COMPILER.md documented: sibling consumers of one
intermediate no longer force a refusal (the intermediate is *promoted*
to a group output — the multi-output mechanism "Operator Fusion in
XLA: Analysis and Evaluation" (PAPERS.md) uses to stop siblings from
duplicating work), and a fusible consumer chain hanging off a
``dot_general`` is absorbed into the producer's region so the matmul
epilogue runs in the output tile instead of round-tripping HBM — the
across-compute-boundary fusion FlashFuser (PAPERS.md) shows is where
the remaining bytes are.

Grouping (a dataflow walk over the analysis-engine users map):

* A group grows upward from a single fusible ROOT op: a producer is
  absorbed as an *internal* member when every user of every one of its
  results is in-group OR the result can be **promoted to a group
  output** — legal only when every external user sits *after* the
  splice point (the root's program position), so the multi-result
  fused op still defines every promoted value before its first read.
  Program outputs count as always-after. Pure layout plumbing
  (broadcast/reshape/transpose/convert) may instead be absorbed as a
  *duplicable* member: the original op stays in the program for its
  external users and the group replays a private copy, reading the
  producer's (never larger) inputs instead of its materialized output.
  A later DCE sweep removes duplicables that lost their last external
  user.
* One **compute anchor** per group: a ``dot_general`` (or an existing
  ``pt.fused_region`` — regions compose) whose users all satisfy the
  same in-group-or-promoted test may be absorbed internally, making
  the group an *epilogue* region — the anchor's result write dies in
  the fused kernel's output tile (unless promoted) and growth
  continues through the anchor's own producers. The anchor is NEVER
  duplicated (an external pre-splice user keeps it out of the group
  entirely) and never roots a group.
* Fusible ops are elementwise math, layout plumbing, and reduces
  (reduce epilogues terminate a chain; a reduce may also sit mid-group
  when its consumers all fused). Never fusible: ops with jax effects
  or a paged-KV ``attrs["effect"]`` stamp, ``pt.*`` fused dispatch ops
  other than this pass's own ``pt.fused_region`` (fusion never crosses
  a routed-kernel boundary), ops carrying nested jaxprs
  (scan/pjit/custom_* — the pass does not descend into sub-jaxprs),
  and ops touching sharding-annotated values (fuse runs before the
  sharding passes; annotated dataflow stays op-granular so
  shard_search/shard_prop still see every conflict and propagation
  frontier) — the sharded wall applies to anchors too.
* Groups are capped at ``MAX_GROUP_OPS`` members and
  ``MAX_GROUP_OUTS`` results so fused bodies stay CSE/cache-friendly;
  a group that would expose more results re-plans under the v1
  single-output discipline instead. A group needs >= 2 members — a
  singleton saves nothing by construction.

Commit criterion (strict): ``CostModel.group_bytes_saved`` — extended
to price multi-result boundaries (each promoted result written once) —
compares the unfused members' summed operand+result traffic against
the fused op's boundary traffic; anchored groups price through
``CostModel.epilogue_bytes_saved`` (the anchor's result write + the
epilogue chain's reads eliminated, operand reads cancelling). Either
way a group commits only on a strict predicted bytes decrease.

Each committed group becomes one ``pt.fused_region`` op whose callable
binds the replayed sub-jaxpr through a single ``jax.jit(inline=True)``
call under a ``jax.named_scope`` (profiler attribution:
``pir.fuse.<program>.g<id>``). The op carries
``attrs["fusion_group"]`` provenance — ``kind`` (``chain`` |
``multi_output`` | ``epilogue``), member op names, result count and
predicted bytes saved — which the printer shows, the canonical hash
keys (fusion decisions change compile-cache keys automatically), and
``CompileReport.summary()`` counts (total and by kind).

Failure contract, same shape as every other pass:

* per-group: any failure while building/validating one group (including
  an injected ``compile.fuse`` fault) skips THAT group — its ops replay
  unfused, every other group stays committed, the compile stays on the
  PIR path;
* whole-pass: a failure in the planning walk itself (or an injected
  fault at the pass entry, hit 1) raises the typed ``FusionPassError``
  and pipeline.compile_flat degrades that compile to plain ``jax.jit``,
  counted ``pir_fallback_total{stage="fuse"}``.

Every group is additionally verifier-gated twice: a pre-commit
``jax.eval_shape`` of the fused body must re-derive exactly the
stamped result types (the type-mismatch rule's check, run per group so
a bad group falls back alone), and the full PR-9 rule wall runs after
the pass under ``FLAGS_pir_verify``.
"""

from __future__ import annotations

import time

from .ir import Operation, Program
from .passes import Pass, PassResult

__all__ = ["FusionPass", "FusionPassError", "FUSIBLE_ELEMENTWISE",
           "FUSIBLE_LAYOUT", "FUSIBLE_REDUCE", "FUSIBLE_ANCHORS",
           "MAX_GROUP_OPS", "MAX_GROUP_OUTS", "GROUP_KINDS"]


class FusionPassError(RuntimeError):
    """The fuse pass failed wholesale (planning-walk bug or an injected
    ``compile.fuse`` fault at the pass entry). compile_flat catches this
    type and degrades that compile to plain jax.jit under
    ``pir_fallback_total{stage="fuse"}`` — per-group failures never
    raise it."""


# elementwise math: one output element reads the aligned input elements
# only — the memory-bound shapes whose intermediates a fused kernel
# keeps in registers/VMEM
FUSIBLE_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow",
    "integer_pow", "exp", "exp2", "expm1", "log", "log1p", "tanh",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
    "cosh", "sqrt", "rsqrt", "cbrt", "logistic", "erf", "erf_inv",
    "erfc", "abs", "neg", "sign", "floor", "ceil", "round", "clamp",
    "square", "is_finite", "not", "and", "or", "xor", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "eq", "ne", "lt",
    "le", "gt", "ge", "select_n", "nextafter", "copy",
})

# layout/dtype plumbing: transparent to the math, free to recompute —
# the duplicable set (absorbed even with external users, when the
# replayed read is not wider than the materialized output)
FUSIBLE_LAYOUT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
    "squeeze", "rev", "stop_gradient",
})

# reduce epilogues: an elementwise chain folding into a (much smaller)
# reduced result fuses the chain's intermediates away; a reduce may
# also sit mid-group (rmsnorm) when its consumers all fused
FUSIBLE_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or",
})

_FUSIBLE = FUSIBLE_ELEMENTWISE | FUSIBLE_LAYOUT | FUSIBLE_REDUCE

# compute anchors: compute-intensive (or already-fused) producers whose
# fusible consumer chain may absorb them — at most ONE per group, never
# duplicated, never a root. "pt.fused_region" makes regions compose: a
# chain hanging off an already-committed region joins that region.
FUSIBLE_ANCHORS = frozenset({"dot_general", "pt.fused_region"})

# provenance kinds a committed group may carry (closed set; bench and
# chaos key on these literals)
GROUP_KINDS = ("chain", "multi_output", "epilogue")

# group size cap: fused jaxprs past this stop being CSE/compile-cache
# friendly (and the greedy walk's win saturates long before it)
MAX_GROUP_OPS = 24

# result cap: a group promoting more outputs than this re-plans under
# the v1 single-output discipline (every promoted result is an HBM
# write — past a handful the multi-output form stops paying and the
# fused-op signature stops being cache-friendly)
MAX_GROUP_OUTS = 8

# minimum members: a singleton group has identical boundary and member
# traffic — structurally refused before pricing
_MIN_GROUP_OPS = 2


class _Group:
    """One committed-candidate fusion group (planning output)."""

    __slots__ = ("root", "internal", "dups", "members", "boundary",
                 "outs", "bytes_saved", "kind", "anchor")

    def __init__(self, root, internal, dups, members, boundary, outs,
                 bytes_saved, kind="chain", anchor=None):
        self.root = root
        self.internal = internal    # [Operation] removed by the splice
        self.dups = dups            # [Operation] replayed, left in place
        self.members = members      # internal + dups, program order
        self.boundary = boundary    # [Value] fused-op operands
        self.outs = outs            # [Value] fused-op results (root's +
        #                             promoted intermediates)
        self.bytes_saved = bytes_saved
        self.kind = kind            # chain | multi_output | epilogue
        self.anchor = anchor        # the absorbed compute op, or None


class FusionPass(Pass):
    """Cost-guided producer-consumer auto-fusion (module docstring has
    the full contract)."""

    name = "fuse"

    def __init__(self, cost_model=None):
        if cost_model is None:
            from .analysis import CostModel
            cost_model = CostModel()
        self.cost = cost_model

    # -- fusibility ---------------------------------------------------------
    @staticmethod
    def _fusible(op: Operation) -> bool:
        if op.eqn is None or op.fn is not None:
            return False            # pt.* dispatch ops are walls
        if op.name not in _FUSIBLE:
            return False
        if op.has_effects() or op.attrs.get("effect") is not None:
            return False            # paged-KV order must stay visible
        if any(v.sharding is not None
               for vs in (op.inputs, op.outputs) for v in vs):
            # fuse runs BEFORE shard_search/shard_prop: annotated
            # dataflow stays op-granular so those passes still see every
            # annotation conflict and propagation frontier. Only chains
            # touching user-annotated inputs refuse — the (unannotated)
            # rest of a sharded program fuses normally.
            return False
        from .analysis import _inner_jaxprs
        if _inner_jaxprs(op.eqn.params):
            return False            # no descent into sub-jaxprs
        return True

    @staticmethod
    def _anchor_fusible(op: Operation) -> bool:
        """May ``op`` be absorbed as a group's compute anchor? Only the
        FUSIBLE_ANCHORS names qualify — a dot_general eqn or one of this
        pass's own pt.fused_region ops — and the sharding / effect walls
        hold exactly as for regular members (an annotated or stateful
        dot stays op-granular)."""
        if op.name not in FUSIBLE_ANCHORS:
            return False
        if op.has_effects() or op.attrs.get("effect") is not None:
            return False
        if any(v.sharding is not None
               for vs in (op.inputs, op.outputs) for v in vs):
            return False            # sharded values are a hard wall
        if op.name == "pt.fused_region":
            return op.fn is not None
        if op.eqn is None or op.fn is not None:
            return False
        from .analysis import _inner_jaxprs
        return not _inner_jaxprs(op.eqn.params)

    @staticmethod
    def _value_bytes(values) -> float:
        from .analysis import CostModel as _CM
        return _CM._value_bytes(values)

    # -- planning (no mutation) ---------------------------------------------
    def _plan(self, prog: Program) -> list:
        users = prog.users()
        index = {id(op): i for i, op in enumerate(prog.ops)}
        claimed: set[int] = set()
        anchors_ok = self._anchors_allowed()
        plans = []
        for root in reversed(prog.ops):
            if id(root) in claimed or not self._fusible(root):
                continue
            g = self._grow(prog, root, users, claimed, index,
                           anchors_ok=anchors_ok)
            if g is not None and len(g.outs) > MAX_GROUP_OUTS:
                # too many promoted results: re-plan this root under the
                # v1 single-output discipline (never worse than PR 16)
                g = self._grow(prog, root, users, claimed, index,
                               promote=False, anchors_ok=anchors_ok)
            if g is None:
                continue
            # claim EVERY member — dups included. A dup stays in the
            # program, but if a later-planned group were allowed to
            # absorb it internally, that group would also internalize
            # (and remove) the dup's producers, dangling this group's
            # boundary reads of those producers' outputs.
            claimed.update(id(op) for op in g.members)
            plans.append(g)
        plans.reverse()             # program order -> deterministic gids
        return plans

    @staticmethod
    def _anchors_allowed() -> bool:
        """Epilogue absorption is disabled while a sharding SEARCH
        scope is active: the search prices the implied all-reduce of a
        sharded contraction off ``dot_general`` eqns (shard_search
        predict_seconds), so absorbing the dot into an opaque region
        would hide that comm term and skew the argmin toward TP.
        Anchors stay op-granular for the search to see; the chains
        around them still fuse."""
        try:
            from . import shard_prop as _sp
            return not (_sp.current_mesh() is not None
                        and _sp.current_search())
        except Exception:  # noqa: BLE001 — no scope machinery: allow
            return True

    def _grow(self, prog, root, users, claimed, index, promote=True,
              anchors_ok=True):
        internal: dict[int, Operation] = {id(root): root}
        dups: dict[int, Operation] = {}
        anchor: list = [None]       # at most one compute anchor
        root_idx = index[id(root)]

        def absorbable(p):
            return (id(p) not in internal and id(p) not in dups
                    and id(p) not in claimed)

        def users_ok(p):
            # internal absorption legality: every user of every result
            # is in-group, or the result is promotable — every external
            # user sits AFTER the splice point (the root's position), so
            # the fused op still defines it before its first read.
            # Program outputs (the None sentinel) are always-after.
            # Without promotion (v1 re-plan) external users refuse.
            for o in p.outputs:
                for u in users.get(o, ()):
                    if u is not None and id(u) in internal:
                        continue
                    if not promote:
                        return False
                    if u is not None and index.get(id(u), -1) <= root_idx:
                        return False
            return True

        changed = True
        while changed and len(internal) + len(dups) < MAX_GROUP_OPS:
            changed = False
            frontier = list(internal.values()) + list(dups.values())
            for op in frontier:
                for v in op.inputs:
                    p = v.op
                    if p is None or not absorbable(p):
                        continue
                    if len(internal) + len(dups) >= MAX_GROUP_OPS:
                        break
                    if self._fusible(p) and users_ok(p):
                        internal[id(p)] = p
                        changed = True
                    elif self._fusible(p) and p.name in FUSIBLE_LAYOUT \
                            and self._value_bytes(p.inputs) \
                            <= self._value_bytes(p.outputs):
                        # duplicable: replay privately, original stays
                        # for its external users (DCE reaps it later if
                        # they disappear). The byte guard keeps e.g. a
                        # downcast's wide input off the fused boundary.
                        dups[id(p)] = p
                        changed = True
                    elif anchors_ok and anchor[0] is None \
                            and self._anchor_fusible(p) and users_ok(p):
                        # epilogue absorption: the compute anchor joins
                        # internally (never duplicated — users_ok means
                        # no pre-splice external reader needs the
                        # original), and growth continues through its
                        # producers
                        internal[id(p)] = p
                        anchor[0] = p
                        changed = True

        member_ids = set(internal) | set(dups)
        if len(member_ids) < _MIN_GROUP_OPS:
            return None
        members = [op for op in prog.ops if id(op) in member_ids]
        internal_ordered = [op for op in members if id(op) in internal]
        dups_ordered = [op for op in members if id(op) in dups]
        boundary, seen = [], set()
        for op in members:
            for v in op.inputs:
                if v.op is not None and id(v.op) in member_ids:
                    continue        # computed inside the replay
                if id(v) not in seen:
                    seen.add(id(v))
                    boundary.append(v)
        # group results: every internal result some non-member still
        # reads (or a program output) is promoted, in program order —
        # the root's live results plus any sibling-shared intermediate
        outs = []
        for op in internal_ordered:
            for o in op.outputs:
                if any(u is None or id(u) not in internal
                       for u in users.get(o, ())):
                    outs.append(o)
        if not outs:
            return None             # fully dead group: DCE's job, not ours
        if anchor[0] is not None:
            kind = "epilogue"
            saved = self.cost.epilogue_bytes_saved(
                anchor[0], internal_ordered, boundary, outs)
        else:
            kind = "multi_output" if len(outs) > 1 else "chain"
            saved = self.cost.group_bytes_saved(internal_ordered,
                                                boundary, outs)
        if saved <= 0:
            return None             # strict decrease or no commit
        return _Group(root, internal_ordered, dups_ordered, members,
                      boundary, outs, saved, kind=kind, anchor=anchor[0])

    # -- commit (one mutation at the end; fallible work first) --------------
    def _commit(self, prog: Program, gid: int, g: _Group) -> Operation:
        import jax
        boundary, outs, members = g.boundary, g.outs, g.members
        out_ids = [id(v) for v in outs]

        def fused_body(*args):
            env = {}
            for v, a in zip(boundary, args):
                env[id(v)] = a
            for op in members:
                ins = [env[id(v)] for v in op.inputs]
                for v, o in zip(op.outputs, op.evaluate(ins)):
                    env[id(v)] = o
            return tuple(env[i] for i in out_ids)

        fused_body.__name__ = f"fused_region_g{gid}"
        # one inlined jit call: the body lands in the outer XLA program
        # as a single sub-jaxpr (no separate dispatch), named for the
        # profiler
        jitted = jax.jit(fused_body, inline=True)
        scope = f"pir.fuse.{prog.name}.g{gid}"

        def fn(*args):
            with jax.named_scope(scope):
                return jitted(*args)

        fn.__name__ = f"fused_region_g{gid}"

        # per-group verifier gate: the fused body must abstractly
        # re-derive exactly the stamped result types (the type-mismatch
        # rule's check, run NOW so a bad group falls back alone instead
        # of costing the whole compile at the post-pass rule wall)
        in_avals = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for v in boundary]
        derived = jax.eval_shape(lambda *a: fn(*a), *in_avals)
        if len(derived) != len(outs):
            raise RuntimeError(
                f"fused group g{gid} derives {len(derived)} results, "
                f"expected {len(outs)}")
        for v, d in zip(outs, derived):
            if (tuple(d.shape), str(d.dtype)) != (tuple(v.shape),
                                                  str(v.dtype)):
                raise RuntimeError(
                    f"fused group g{gid} result %{v.vid} derives "
                    f"{d.dtype}[{','.join(map(str, d.shape))}], stamped "
                    f"{v.type_str}")

        # roofline provenance: the members' summed flops still happen
        # inside the region (dups replay too), while its HBM traffic is
        # the fused boundary. Stamped here so CostModel._op_cost prices
        # the region honestly — without this, absorbing a dot_general
        # would HIDE its flops from shard_search/overlap/report costing
        # (a fused matmul is not suddenly memory-bound).
        flops = sum(self.cost._op_cost(op).flops for op in members)
        fused_bytes = (self._value_bytes(boundary)
                       + self._value_bytes(outs))
        new_op = Operation(
            "pt.fused_region", list(boundary), outs,
            attrs={"fusion_group": {
                "id": gid,
                "kind": g.kind,
                "ops": [op.name for op in members],
                "outs": len(outs),
                "flops": float(flops),
                "bytes": float(fused_bytes),
                "bytes_saved": int(g.bytes_saved)}},
            fn=fn)
        prog.replace_region(g.internal, new_op)
        return new_op

    # -- the pass -----------------------------------------------------------
    def run(self, prog: Program) -> PassResult:
        from ..observability import span as _span
        from ..observability.catalog import metric as _metric
        from ..resilience.faults import fault_point
        t0 = time.perf_counter()
        committed = skipped = member_ops = 0
        saved_total = 0.0
        kinds = {k: 0 for k in GROUP_KINDS}
        with _span("pir.fuse", program=prog.name, ops=len(prog.ops)):
            try:
                # hit 1 of the chaos seam: a fault HERE (or any planning
                # bug) is a whole-pass failure -> stage="fuse" fallback
                fault_point("compile.fuse", program=prog.name,
                            where="pass")
                plans = self._plan(prog)
            except Exception as e:  # noqa: BLE001 — typed for the pipeline
                raise FusionPassError(
                    f"fuse planning failed for {prog.name!r}: "
                    f"{type(e).__name__}: {e}") from e
            for gid, g in enumerate(plans):
                try:
                    # hits 2..N+1: per-group seam — a fault here skips
                    # THIS group only (its ops replay unfused)
                    fault_point("compile.fuse", program=prog.name,
                                group=gid)
                    self._commit(prog, gid, g)
                except Exception:  # noqa: BLE001 — per-group fallback:
                    skipped += 1   # nothing was mutated for this group
                    continue
                committed += 1
                member_ops += len(g.members)
                saved_total += g.bytes_saved
                kinds[g.kind] += 1
        dt = time.perf_counter() - t0
        try:
            _metric("pir_fuse_seconds").observe(dt)
            if committed:
                _metric("pir_fusion_groups_total",
                        program=prog.name).inc(committed)
                _metric("pir_fusion_bytes_saved",
                        program=prog.name).inc(saved_total)
                for k, n in kinds.items():
                    if n:
                        _metric("pir_fusion_groups_by_kind_total",
                                program=prog.name, kind=k).inc(n)
        except Exception:  # noqa: BLE001 — metrics never cost a compile
            pass
        prog._fusion = {"groups": committed,
                        "bytes_saved": int(saved_total),
                        "skipped": skipped,
                        "kinds": {k: n for k, n in kinds.items() if n}}
        notes = (f"groups={committed} member_ops={member_ops} "
                 f"bytes_saved={int(saved_total)}")
        if committed:
            notes += " kinds=" + ",".join(
                f"{k}:{n}" for k, n in kinds.items() if n)
        if skipped:
            notes += f" skipped={skipped}"
        return PassResult(committed, notes)
