"""paddle_tpu.pir — the PIR-lite compiler layer.

reference: paddle/pir/ (Program/Operation/Value SSA IR,
pir::PassManager, DRR pattern rewriting) + the PIR serialize layer.
The survey's layer 2, previously the only surveyed layer with no
in-repo analog (COVERAGE.md row 12).

Three pieces (see COMPILER.md for the full spec):

* **capture** (`pir.capture`): one jax trace lowers a program to a
  small SSA IR with stable canonical hashing;
* **PassManager** (`pir.passes` / `pir.patterns`): ordered,
  flag-toggleable, observability-instrumented passes — DCE, constant
  folding, CSE, and DRR-lite pattern rewriting whose production
  patterns route sdpa subgraphs through the attention backend router
  and fuse rms epilogues into the Pallas flash kernel;
* **compile cache** (`pir.cache` / `pir.pipeline`): persistent,
  sha256-verified, LRU-capped StableHLO artifacts keyed by
  (canonical IR hash, sharding, flags, jax version, platform).

jit.to_static and the serving engine compile through
``pipeline.compile_flat`` / ``pipeline.pir_jit``.
"""

from .analysis import (DataflowAnalysis, FlatLattice, Lattice, Liveness,
                       ShapeDtypeInference, ShardingConsistency,
                       check_donation_safety)
from .cache import (CompileCache, CompileCacheCorruptionError, cache_key,
                    default_cache, stats_snapshot)
from .capture import capture, from_closed_jaxpr
from .fuse import (FUSIBLE_ELEMENTWISE, FUSIBLE_LAYOUT, FUSIBLE_REDUCE,
                   FusionPass, FusionPassError)
from .ir import Operation, Program, Value
from .mutate import CORRUPTIONS, SkipCorruption, corrupt
from .passes import (CommonSubexprElimination, ConstantFolding,
                     DeadCodeElimination, Pass, PassManager, PassResult)
from .patterns import (PatternRewriter, RewritePattern, RmsEpiloguePattern,
                       SdpaRoutePattern)
from .pipeline import CompileReport, compile_flat, pir_jit
from .verifier import (EFFECT_SCOPES, RULES, IRVerificationError,
                       verify_mode, verify_program)

__all__ = [
    "Program", "Operation", "Value",
    "capture", "from_closed_jaxpr",
    "Pass", "PassResult", "PassManager",
    "DeadCodeElimination", "ConstantFolding", "CommonSubexprElimination",
    "RewritePattern", "PatternRewriter", "SdpaRoutePattern",
    "RmsEpiloguePattern",
    "FusionPass", "FusionPassError", "FUSIBLE_ELEMENTWISE",
    "FUSIBLE_LAYOUT", "FUSIBLE_REDUCE",
    "CompileCache", "CompileCacheCorruptionError", "cache_key",
    "default_cache", "stats_snapshot",
    "CompileReport", "compile_flat", "pir_jit",
    "RULES", "EFFECT_SCOPES", "IRVerificationError", "verify_program",
    "verify_mode",
    "DataflowAnalysis", "Lattice", "FlatLattice", "ShapeDtypeInference",
    "Liveness", "ShardingConsistency", "check_donation_safety",
    "CORRUPTIONS", "SkipCorruption", "corrupt",
]
