"""The compile pipeline: capture -> PassManager -> cache -> XLA.

This is the layer jit.to_static and the serving engine call instead of
raw ``jax.jit``: the traced jaxpr is lowered to a pir.Program, the
instrumented pass pipeline rewrites it (DCE / fold / CSE / DRR
patterns), and the persistent compile cache is consulted pre-XLA —
a warm hit deserializes a StableHLO artifact and skips lowering +
backend compilation; a miss jits the rewritten program's interpreter
and writes the artifact back (atomic, verified, LRU-capped).

Every failure degrades, never breaks: any pipeline error falls back to
plain ``jax.jit`` of the original function, counted in
``pir_fallback_total{stage}`` (graph-break ConcretizationTypeErrors
propagate untouched — that contract belongs to to_static).
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.export as _jax_export

from .cache import (CompileCacheCorruptionError, _bump, _metric, cache_key,
                    default_cache)
from .capture import capture
from .fuse import FusionPassError
from .passes import PassManager
from .verifier import IRVerificationError, verify_mode, verify_program

__all__ = ["CompileReport", "compile_flat", "pir_jit"]


class CompileReport:
    """What the pipeline did for one program — attached to
    StaticFunction/_PirJit for tests, bench rows and the IR dump tool."""

    __slots__ = ("name", "key", "cache", "pass_report", "program",
                 "captured_ops", "final_ops", "pattern_counts", "fallback",
                 "cost", "shard_decision", "shard_predicted_s",
                 "fusion_groups", "fusion_bytes_saved", "fusion_kinds")

    def __init__(self, name):
        self.name = name
        self.key = None
        self.cache = "off"          # off|miss|hit|bypass:<why>|error:<why>
        self.pass_report = {}
        self.program = None         # the post-pass pir.Program
        self.captured_ops = 0
        self.final_ops = 0
        self.pattern_counts = {}
        self.fallback = None        # stage name when pir fell back
        self.cost = None            # analysis.ProgramCost of the final IR
        self.shard_decision = None  # shard_search argmin (e.g. "dp+tp")
        self.shard_predicted_s = None
        self.fusion_groups = 0      # pt.fused_region groups committed
        self.fusion_bytes_saved = 0  # predicted HBM bytes saved by fuse
        self.fusion_kinds = {}      # committed groups by provenance kind

    def summary(self) -> dict:
        out = {"name": self.name, "cache": self.cache,
               "captured_ops": self.captured_ops,
               "final_ops": self.final_ops,
               "patterns": dict(self.pattern_counts),
               "passes": {k: {"edits": v["edits"],
                              "seconds": round(v["seconds"], 6)}
                          for k, v in self.pass_report.items()},
               "cost": self.cost.summary() if self.cost else None,
               "fusion_groups": self.fusion_groups,
               "fusion_bytes_saved": self.fusion_bytes_saved,
               "fusion_kinds": dict(self.fusion_kinds),
               "fallback": self.fallback}
        if self.shard_decision is not None:
            out["shard_decision"] = self.shard_decision
            out["shard_predicted_s"] = self.shard_predicted_s
        return out


def _avals(flat_args):
    import jax.numpy as jnp
    return [jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype)
            for a in flat_args]


def compile_flat(flat_fn: Callable, flat_args: list, *, name: str,
                 sharding: str = "replicated", donate_argnums=None,
                 vjp_order: int = 1, extra_key: Optional[dict] = None,
                 input_shardings: Optional[list] = None):
    """Compile ``flat_fn(*flat_leaves) -> tuple`` through the pipeline.
    Returns (callable, CompileReport). Raises only what tracing raises
    (e.g. ConcretizationTypeError); pipeline-internal failures degrade
    to plain jax.jit with the fallback stage recorded.

    ``input_shardings`` optionally carries one sharding spec (mesh-axis
    tuple) or None per flat leaf: the sharding-propagation pass spreads
    them through the program, and replay re-asserts them under the
    active ``shard_prop.mesh_scope``. Annotated compiles (and compiles
    under a mesh scope, whose search pass may annotate) fold the specs
    + mesh shape into the cache key — sharded artifacts are never
    shared across meshes."""
    report = CompileReport(name)
    try:
        from .shard_prop import current_mesh, sharding_cache_tag
        if input_shardings or current_mesh() is not None:
            sharding = (f"{sharding}|"
                        f"{sharding_cache_tag(input_shardings or [])}")
    except Exception:  # noqa: BLE001 — key tagging may never break compile
        pass
    try:
        prog, _ = capture(flat_fn, *flat_args, name=name)
        report.captured_ops = prog.num_ops()
        from jax._src.core import Tracer
        if any(isinstance(c, Tracer) for c in prog.constants.values()):
            # captured under an OUTER jax trace (e.g. nested to_static):
            # tracer-valued consts must not leak into a host-side program
            raise RuntimeError("program closes over tracers "
                               "(nested trace); pir requires concrete "
                               "constants")
    except jax.errors.ConcretizationTypeError:
        raise                       # graph-break contract: caller handles
    except Exception as e:  # noqa: BLE001 — degrade, never break compile
        return _fallback(flat_fn, donate_argnums, report, "capture", e)

    if input_shardings:
        try:
            from .shard_prop import annotate_inputs
            annotate_inputs(prog, input_shardings)
        except Exception as e:  # noqa: BLE001 — bad specs drop the hints,
            # not the compile: the program stays valid, just unannotated
            for v in prog.inputs:
                v.sharding = None
            warnings.warn(f"input shardings for {name!r} dropped: {e!r}",
                          RuntimeWarning, stacklevel=2)

    try:
        if verify_mode() != "off":
            # capture-boundary verify; donated compiles also get the
            # static donation-alias check here (the program the passes
            # rewrite must already be double-buffer safe)
            verify_program(prog, donate_argnums=donate_argnums,
                           where="capture")
    except Exception as e:  # noqa: BLE001 — IRVerificationError, or a bad
        # FLAGS_pir_verify value: rejecting a program may only ever cost
        # the pir path, never the compile
        return _fallback(flat_fn, donate_argnums, report, "verify", e)

    try:
        pm = PassManager.default()
        report.pass_report = pm.run(prog)
        report.final_ops = prog.num_ops()
        report.program = prog
        try:
            from .analysis import CostModel
            report.cost = CostModel().analyze(prog)
        except Exception:  # noqa: BLE001 — pricing may never cost a compile
            report.cost = None
        pat = report.pass_report.get("pattern", {})
        report.pattern_counts = dict(
            p.split("=") for p in (pat.get("notes") or "").split()
            if "=" in p)
        report.pattern_counts = {k: int(v)
                                 for k, v in report.pattern_counts.items()}
        decision = getattr(prog, "_shard_search", None)
        if decision is not None:
            report.shard_decision = decision["decision"]
            report.shard_predicted_s = decision["predicted_seconds"]
        fusion = getattr(prog, "_fusion", None)
        if fusion is not None:
            report.fusion_groups = fusion["groups"]
            report.fusion_bytes_saved = fusion["bytes_saved"]
            report.fusion_kinds = dict(fusion.get("kinds", {}))
    except FusionPassError as e:
        # the fuse pass failed wholesale (planning walk, not one group):
        # distinct stage so fusion regressions are separable from other
        # pass crashes on dashboards and in the chaos drill
        return _fallback(flat_fn, donate_argnums, report, "fuse", e)
    except IRVerificationError as e:
        # a pass produced a malformed program: the verifier caught it
        # before the evaluator could compile it — distinct stage so the
        # chaos drill and dashboards separate "pass crashed" from "pass
        # produced bad IR"
        return _fallback(flat_fn, donate_argnums, report, "verify", e)
    except Exception as e:  # noqa: BLE001
        return _fallback(flat_fn, donate_argnums, report, "passes", e)

    try:
        evaluator = _make_evaluator(prog)
        jit_kwargs = {}
        if donate_argnums:
            jit_kwargs["donate_argnums"] = tuple(donate_argnums)
        jitted = jax.jit(evaluator, **jit_kwargs)
    except Exception as e:  # noqa: BLE001
        return _fallback(flat_fn, donate_argnums, report, "evaluator", e)

    cache = default_cache()
    if cache is None:
        report.cache = "off"
        return jitted, report
    if donate_argnums:
        # a deserialized Exported cannot express donation; on device the
        # double-buffering would silently cost HBM, so donated programs
        # keep the pass pipeline but bypass the artifact store
        report.cache = "bypass:donate"
        return jitted, report

    report.key = cache_key(prog.canonical_hash(), sharding=sharding,
                           extra=extra_key)
    loaded = _cache_read(cache, report)
    if loaded is not None:
        return loaded, report

    if not report.cache.startswith("error:"):
        report.cache = "miss"
    _bump("miss")
    _metric("compile_cache_miss_total").inc()
    _flight("miss", report.name)
    _cache_write(cache, report, jitted, flat_args, vjp_order)
    return jitted, report


def _flight(status, name):
    """Flight-recorder compile-cache probe event (hit/miss/corrupt/
    store) — black-box context for a postmortem ('was the engine cold-
    compiling when it died?'). Guarded: never breaks a compile."""
    try:
        from ..observability.recorder import get_recorder
        rec = get_recorder()
        if rec.enabled:
            rec.record("compile_cache", status=status, program=name)
    except Exception:  # noqa: BLE001
        pass


def _make_evaluator(prog):
    mesh = getattr(prog, "_mesh", None)
    if mesh is None:
        def evaluate(*flat):
            return prog.bind(*flat)
    else:
        # the propagation pass pinned the scope mesh on the program:
        # trace (and replay) under it so every annotated value's
        # with_sharding_constraint lands in the XLA program even when
        # the caller dispatches outside the original mesh scope
        def evaluate(*flat):
            from .shard_prop import mesh_scope
            with mesh_scope(mesh):
                return prog.bind(*flat)
    evaluate.__name__ = f"pir_eval_{prog.name}"
    return evaluate


def _fallback(flat_fn, donate_argnums, report, stage, err):
    report.fallback = stage
    _metric("pir_fallback_total", stage=stage).inc()
    warnings.warn(
        f"pir pipeline fell back to plain jax.jit for "
        f"{report.name!r} at stage {stage!r}: {err!r}",
        RuntimeWarning, stacklevel=3)
    kw = {"donate_argnums": tuple(donate_argnums)} if donate_argnums else {}
    return jax.jit(flat_fn, **kw), report


def _cache_read(cache, report):
    """Returns the warm callable or None. Corruption is a typed, counted
    error that degrades to recompile (the artifact is dropped)."""
    try:
        hit = cache.get(report.key)
    except CompileCacheCorruptionError as e:
        _bump("corrupt")
        _metric("compile_cache_corrupt_total").inc()
        _flight("corrupt", report.name)
        warnings.warn(f"{e}; recompiling", RuntimeWarning, stacklevel=3)
        cache.drop(report.key)
        return None
    except Exception as e:  # noqa: BLE001 — IO trouble or ANY injected
        # class: a cache read may only ever cost a recompile, never
        # break the compile itself
        _bump("read_error")
        report.cache = f"error:read:{type(e).__name__}"
        return None
    if hit is None:
        return None
    payload, meta = hit
    try:
        exported = _jax_export.deserialize(payload)
    except Exception as e:  # noqa: BLE001 — undeserializable == corrupt
        _bump("corrupt")
        _metric("compile_cache_corrupt_total").inc()
        _flight("corrupt", report.name)
        warnings.warn(
            f"compile-cache artifact {report.key[:12]} verified but did "
            f"not deserialize ({e!r}); recompiling", RuntimeWarning,
            stacklevel=3)
        cache.drop(report.key)
        return None
    report.cache = "hit"
    _bump("hit")
    _metric("compile_cache_hit_total").inc()
    _flight("hit", report.name)

    def warm(*flat):
        return exported.call(*flat)
    return warm


def _cache_write(cache, report, jitted, flat_args, vjp_order):
    try:
        exported = _jax_export.export(jitted)(*_avals(flat_args))
        payload = exported.serialize(vjp_order=vjp_order)
    except Exception as e:  # noqa: BLE001 — unexportable program: no artifact
        report.cache = f"miss:unexportable:{type(e).__name__}"
        return
    try:
        cache.put(report.key, payload,
                  meta={"name": report.name,
                        "captured_ops": report.captured_ops,
                        "final_ops": report.final_ops,
                        "patterns": report.pattern_counts})
    except Exception as e:  # noqa: BLE001 — write failures degrade, counted
        _bump("write_error")
        report.cache = f"error:write:{type(e).__name__}"
        warnings.warn(
            f"compile-cache write failed for {report.name!r} "
            f"({e!r}); continuing uncached", RuntimeWarning, stacklevel=4)
        return
    _bump("write")
    _metric("compile_cache_write_total").inc()
    _flight("store", report.name)


# --------------------------------------------------------------------------
# pytree-level lazy wrapper (serving engine warm start, tools)
# --------------------------------------------------------------------------

class pir_jit:
    """Drop-in for ``jax.jit(fn)`` over pytree args: on the first call
    the concrete args fix the signature and the pipeline compiles (or
    warm-loads) the program; later calls must match the first call's
    tree structure (the jax.jit contract serving already relies on)."""

    def __init__(self, fn, *, name=None, sharding="replicated",
                 donate_argnums=None, vjp_order=0, extra_key=None,
                 input_shardings=None, sharding_rules=None):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "pir_jit")
        self._sharding = sharding
        self._donate = donate_argnums
        self._vjp_order = vjp_order
        self._extra = extra_key
        # sharding annotations for the propagation pass: either a flat
        # per-leaf spec list (input_shardings) or SNIPPETS-style
        # [(regex, spec)] rules matched on the args tree paths at the
        # first call (sharding_rules); rules win if both are given
        self._input_shardings = input_shardings
        self._sharding_rules = sharding_rules
        self._compiled = None
        self._in_treedef = None
        self._out_treedef = None
        self.report: Optional[CompileReport] = None

    def _build(self, args):
        from ..framework import flags as _flags
        flat, in_tree = jax.tree_util.tree_flatten(args)
        self._in_treedef = in_tree
        out_box = {}

        def flat_fn(*leaves):
            a = jax.tree_util.tree_unflatten(in_tree, leaves)
            out = self._fn(*a)
            out_flat, out_tree = jax.tree_util.tree_flatten(out)
            out_box["tree"] = out_tree
            return tuple(out_flat)

        donate_flat = None
        if self._donate:
            donate_flat = []
            off = 0
            for i, a in enumerate(args):
                leaves = jax.tree_util.tree_flatten(a)[0]
                if i in self._donate:
                    donate_flat.extend(range(off, off + len(leaves)))
                off += len(leaves)
        specs = self._input_shardings
        if self._sharding_rules is not None:
            try:
                from .shard_prop import flat_input_specs
                specs = flat_input_specs(args, self._sharding_rules)
            except Exception as e:  # noqa: BLE001 — hints degrade
                warnings.warn(f"sharding rules for {self.name!r} "
                              f"dropped: {e!r}", RuntimeWarning,
                              stacklevel=2)
                specs = None
        if not _flags.flag_value("pir"):
            report = CompileReport(self.name)
            report.cache = "disabled"
            kw = ({"donate_argnums": tuple(donate_flat)}
                  if donate_flat else {})
            compiled, self.report = jax.jit(flat_fn, **kw), report
        else:
            compiled, self.report = compile_flat(
                flat_fn, flat, name=self.name, sharding=self._sharding,
                donate_argnums=donate_flat, vjp_order=self._vjp_order,
                extra_key=self._extra, input_shardings=specs)
        if "tree" not in out_box:
            # warm hit / fallback never ran flat_fn's python: learn the
            # out tree from an abstract trace of the original fn
            jax.eval_shape(lambda *a: flat_fn(*a), *flat)
        self._out_treedef = out_box["tree"]
        self._compiled = compiled

    def __call__(self, *args):
        if self._compiled is None:
            self._build(args)
        flat = jax.tree_util.tree_flatten(args)[0]
        out_flat = self._compiled(*flat)
        return jax.tree_util.tree_unflatten(self._out_treedef, out_flat)
