"""Cost-driven sharding search: choose the sharding per captured program.

reference: the ROADMAP's "Small Language Models as Compiler Experts"
(arXiv:2512.19250) framing with the deterministic CostModel over the
baked hardware ledger standing in for the expert. The caller opens a
``shard_prop.mesh_scope(mesh, search=[(name, flat_specs), ...])`` with
a bounded strategy space — typically DP / TP / DP+TP input-spec lists
built per rule group with ``shard_prop.flat_input_specs`` — and this
pass prices every candidate by dry-running the propagation fixpoint
(``propagate_facts``; the program is never mutated while searching)
through a roofline+interconnect estimate:

  t(c) = Σ_op max(flops/(eff·shards), bytes/(hbm·shards))    [compute]
       + Σ collectives wire_bytes/ici                        [captured]
       + Σ sharded-contraction dots 2·out_bytes/ici          [implied
       + Σ reshard stamps out_bytes/ici                       comm]

The argmin's specs are committed to the program inputs (the
shard_prop pass, next in the pipeline, completes the propagation) and
the decision + predicted seconds land on the CompileReport and in a
``pir.shard_search`` span. An implicit "replicated" candidate is
always priced, so the search can decide sharding is not worth it.
User annotations win: if any program input already carries a sharding,
the search declines. The candidate list is truncated to
``MAX_CANDIDATES`` (bounded space by construction, bounded again here).
"""

from __future__ import annotations

import time

from .analysis import CostModel
from .ir import Program
from .passes import Pass, PassResult
from . import shard_prop as _sp

__all__ = ["ShardingSearch", "predict_seconds", "MAX_CANDIDATES"]

MAX_CANDIDATES = 16


def predict_seconds(prog: Program, facts: dict, stamps: dict,
                    mesh_axes: dict, cost: CostModel) -> float:
    """Roofline+ICI price of one candidate assignment (facts/stamps
    from a dry ``propagate_facts`` run)."""
    op_costs = cost.run(prog)
    eff = cost.roofline["peak_flops"] * cost.roofline["efficiency"]
    hbm = cost.roofline["hbm_bps"]
    ici = cost.interconnect["ici_bps"]
    total = 0.0
    for op in prog.ops:
        c = op_costs[id(op)]
        shards = 1
        spec = facts.get(id(op.outputs[0])) if op.outputs else None
        if spec:
            for a in spec:
                if a is not None:
                    shards *= int(mesh_axes.get(a, 1))
        total += max(c.flops / (eff * shards) if eff > 0 else 0.0,
                     c.bytes / (hbm * shards) if hbm > 0 else 0.0)
        total += cost.comm_seconds(op)
        out_bytes = CostModel._value_bytes(op.outputs)
        if op.eqn is not None and op.eqn.primitive.name == "dot_general":
            # a sharded contraction implies an all-reduce of the result
            try:
                (lc, rc), _ = op.eqn.params["dimension_numbers"]
                ls = facts.get(id(op.inputs[0])) or ()
                rs = facts.get(id(op.inputs[1])) or ()
                if any(d < len(ls) and ls[d] is not None for d in lc) or \
                        any(d < len(rs) and rs[d] is not None for d in rc):
                    total += 2.0 * out_bytes / ici if ici > 0 else 0.0
            except Exception:  # noqa: BLE001 — odd dnums: skip the term
                pass
        rule = stamps.get(id(op))
        if rule is not None and rule.startswith("reshard"):
            total += out_bytes / ici if ici > 0 else 0.0
    return total


class ShardingSearch(Pass):
    """Enumerate the scope's bounded strategy space, price each
    candidate with the CostModel, commit the argmin's input specs.
    Declines (0 edits) outside a mesh scope, without a search space, or
    when the user already annotated an input."""

    name = "shard_search"

    def run(self, prog: Program) -> PassResult:
        mesh = _sp.current_mesh()
        space = _sp.current_search()
        if mesh is None or not space:
            return PassResult(0, "no-search-scope")
        if any(v.sharding is not None for v in prog.inputs):
            return PassResult(0, "user-annotated")
        mesh_axes = _sp._mesh_axis_sizes(mesh)
        cost = CostModel()
        candidates = [("replicated", None)] + list(space)[:MAX_CANDIDATES]
        from ..observability import span as _span
        from ..observability.catalog import metric as _metric
        t0 = time.perf_counter()
        priced: dict = {}
        with _span("pir.shard_search", program=prog.name,
                   candidates=len(candidates)):
            for name, specs in candidates:
                if specs is None:
                    seed: dict = {}
                else:
                    seed = {}
                    for v, spec in zip(prog.inputs, specs):
                        if spec is not None:
                            s = _sp._sanitize(spec, v.shape, mesh_axes)
                            if s is not None:
                                seed[id(v)] = s
                facts, stamps, _, _ = _sp.propagate_facts(
                    prog, seed, cost_model=cost)
                priced[name] = (predict_seconds(prog, facts, stamps,
                                                mesh_axes, cost), specs)
        decision = min(priced, key=lambda n: (priced[n][0], n))
        predicted, specs = priced[decision]
        try:
            _metric("pir_shard_search_seconds").observe(
                time.perf_counter() - t0)
        except Exception:  # noqa: BLE001 — metrics never cost a compile
            pass
        prog._shard_search = {
            "decision": decision,
            "predicted_seconds": predicted,
            "candidates": {n: priced[n][0] for n in sorted(priced)},
        }
        edits = 0
        if specs is not None:
            edits = _sp.annotate_inputs(prog, specs)
        return PassResult(
            edits, f"decision={decision} predicted={predicted:.3g}s "
                   f"candidates={len(priced)}")
