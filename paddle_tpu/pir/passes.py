"""Instrumented pass manager + the scalar optimization passes.

reference: paddle/pir/include/pass/ (pir::PassManager, pass
registration/instrumentation) and the DCE/constant-fold/CSE passes
under paddle/fluid/pir/transforms/.

Every pass run is timed into ``pir_pass_seconds{pass}`` and its edit
count lands in ``pir_pass_edits_total{pass}`` through the observability
catalog, and the whole pipeline is wrapped in spans — the pass layer is
born observable, same discipline as serving/train.

Passes are individually toggleable through ``FLAGS_pir_passes`` (an
ordered comma list; default
"fold,cse,pattern,fuse,dce,shard_search,shard_prop,overlap").
"""

from __future__ import annotations

import time
from typing import Optional

from .ir import Program

__all__ = ["Pass", "PassResult", "PassManager", "DeadCodeElimination",
           "ConstantFolding", "CommonSubexprElimination", "PASSES",
           "PIPELINE_VERSION"]

# bump when pass semantics change in a way that invalidates cached
# artifacts compiled from the rewritten programs (2: sharded replay —
# annotated programs trace with_sharding_constraint into the evaluator)
PIPELINE_VERSION = 2

# The closed pass registry: every name FLAGS_pir_passes may list, with
# its one-line role. tools/static_check.py pins this dict against the
# flag default and the COMPILER.md pass-catalog rows, both directions;
# _registry() maps the same names to classes (and asserts it agrees).
PASSES = {
    "fold": "constant folding (host-evaluates const subgraphs)",
    "cse": "common-subexpression elimination",
    "pattern": "DRR pattern rewriter (fused pt.* ops)",
    "fuse": "cost-guided auto-fusion (pt.fused_region groups)",
    "dce": "dead code elimination",
    "shard_search": "cost-driven sharding search (argmin strategy)",
    "shard_prop": "GSPMD-style sharding propagation to fixpoint",
    "overlap": "collective-overlap scheduling (hide comm under compute)",
}

# outputs larger than this are not materialized by constant folding
_FOLD_MAX_ELEMS = 1 << 20

# call-like primitives whose closed jaxpr is inlined during folding:
# binding them with concrete args would XLA-compile a fresh (never
# cache-hitting, the jaxpr object is new per capture) sub-program per
# to_static; interpreting eqn-by-eqn hits jax's per-primitive impl
# cache instead
_INLINE_CALLS = ("pjit", "closed_call", "custom_jvp_call",
                 "custom_vjp_call", "remat", "checkpoint")

# CSE skips ops carrying a sub-jaxpr bigger than this: the canonical
# attr text would pretty-print the whole body (a scanned model's is
# huge) for a merge that essentially never exists
_CSE_MAX_SUBJAXPR_EQNS = 16


def _closed_jaxpr_param(eqn):
    for k in ("jaxpr", "call_jaxpr"):
        v = eqn.params.get(k)
        if v is not None and hasattr(v, "jaxpr"):
            return v
    return None


def _concrete_eval(closed, args):
    """Interpret a ClosedJaxpr on concrete arrays, inlining nested
    call-like primitives instead of binding them (bind on a call
    primitive with concrete operands compiles the sub-program)."""
    from jax._src.core import Literal
    jaxpr = closed.jaxpr
    env = {}

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for var, val in zip(jaxpr.constvars, closed.consts):
        env[var] = val
    for var, val in zip(jaxpr.invars, args):
        env[var] = val
    for eqn in jaxpr.eqns:
        in_vals = [read(v) for v in eqn.invars]
        sub = (_closed_jaxpr_param(eqn)
               if eqn.primitive.name in _INLINE_CALLS else None)
        if sub is not None:
            out = _concrete_eval(sub, in_vals)
        else:
            prim = eqn.primitive
            subfuns, bind_params = prim.get_bind_params(eqn.params)
            out = prim.bind(*subfuns, *in_vals, **bind_params)
            out = out if prim.multiple_results else [out]
        for var, val in zip(eqn.outvars, out):
            env[var] = val
    return [read(v) for v in jaxpr.outvars]


class PassResult:
    __slots__ = ("changed", "edits", "notes")

    def __init__(self, edits: int = 0, notes: str = ""):
        self.edits = int(edits)
        self.changed = self.edits > 0
        self.notes = notes

    def __repr__(self):
        return f"PassResult(edits={self.edits}, notes={self.notes!r})"


class Pass:
    name = "pass"

    def run(self, prog: Program) -> PassResult:  # pragma: no cover
        raise NotImplementedError


class DeadCodeElimination(Pass):
    """Remove ops none of whose outputs reach a program output (and
    constants nothing reads). Ops with jax effects are pinned live.

    Multi-result ``pt.fused_region`` ops additionally get dead RESULTS
    pruned in place: when a promoted group output loses its last
    consumer (the consumer was itself dead code), the region stays but
    its signature shrinks to the live subset — the fused body is
    wrapped to return only the kept indices, so the dead intermediate's
    HBM write disappears with its reader and the strict post-DCE
    verifier rule (which holds fused regions to per-result liveness)
    stays satisfiable."""

    name = "dce"

    def run(self, prog: Program) -> PassResult:
        live = set(id(v) for v in prog.outputs)
        kept = []
        pruned_results = 0
        for op in reversed(prog.ops):
            if op.has_effects() or any(id(o) in live for o in op.outputs):
                if (op.name == "pt.fused_region" and op.fn is not None
                        and not op.has_effects()
                        and op.attrs.get("effect") is None
                        and len(op.outputs) > 1
                        and any(id(o) not in live for o in op.outputs)):
                    keep = tuple(i for i, o in enumerate(op.outputs)
                                 if id(o) in live)
                    pruned_results += len(op.outputs) - len(keep)
                    op.outputs = [op.outputs[i] for i in keep]
                    inner = op.fn

                    def fn(*args, _inner=inner, _keep=keep):
                        res = _inner(*args)
                        return tuple(res[i] for i in _keep)

                    fn.__name__ = getattr(inner, "__name__", "fused_region")
                    op.fn = fn
                    fg = op.attrs.get("fusion_group")
                    if isinstance(fg, dict):
                        fg["outs"] = len(keep)
                kept.append(op)
                live.update(id(v) for v in op.inputs)
        removed_ops = len(prog.ops) - len(kept)
        prog.ops = kept[::-1]
        live.update(id(v) for v in prog.inputs)
        dead_consts = [v for v in prog.constants if id(v) not in live]
        for v in dead_consts:
            del prog.constants[v]
        notes = f"ops={removed_ops} consts={len(dead_consts)}"
        if pruned_results:
            notes += f" fused_results={pruned_results}"
        return PassResult(removed_ops + len(dead_consts) + pruned_results,
                          notes)


class ConstantFolding(Pass):
    """Evaluate ops whose operands are all constants on the host and
    replace their results with constants. Random/effectful/fused ops
    and oversized results are skipped. This is what turns mask- and
    rope-table subgraphs into literals the pattern matcher can reason
    about (e.g. "is this mask exactly tril?")."""

    name = "fold"

    def run(self, prog: Program) -> PassResult:
        import numpy as np
        lut = {id(v): c for v, c in prog.constants.items()}
        folded = 0
        kept = []
        for op in prog.ops:
            foldable = (
                op.fn is None and not op.has_effects()
                and "random" not in op.name
                and op.inputs and all(id(v) in lut for v in op.inputs)
                and all(int(np.prod(o.shape or (1,))) <= _FOLD_MAX_ELEMS
                        for o in op.outputs))
            # input-free table builders (iota) fold too
            if (not op.inputs and op.fn is None and not op.has_effects()
                    and op.name == "iota"):
                foldable = True
            if not foldable:
                kept.append(op)
                continue
            try:
                in_vals = [lut[id(v)] for v in op.inputs]
                sub = (_closed_jaxpr_param(op.eqn)
                       if op.eqn is not None
                       and op.name in _INLINE_CALLS else None)
                outs = (_concrete_eval(sub, in_vals) if sub is not None
                        else op.evaluate(in_vals))
            except Exception:  # noqa: BLE001 — a non-foldable op just stays
                kept.append(op)
                continue
            for v, o in zip(op.outputs, outs):
                prog.constants[v] = o
                v.op = None
                lut[id(v)] = o
            folded += 1
        prog.ops = kept
        return PassResult(folded, f"ops_folded={folded}")


class CommonSubexprElimination(Pass):
    """Merge ops with identical (name, operands, attrs). Fused and
    effectful ops are skipped; duplicate constants merge by content."""

    name = "cse"

    def run(self, prog: Program) -> PassResult:
        import hashlib

        import numpy as np
        replace: dict[int, object] = {}   # id(old Value) -> Value

        def res(v):
            return replace.get(id(v), v)

        merged = 0
        # constants by content digest
        by_digest: dict[tuple, object] = {}
        for v, c in list(prog.constants.items()):
            arr = np.asarray(c)
            key = (str(arr.dtype), arr.shape,
                   hashlib.sha256(arr.tobytes()).hexdigest())
            first = by_digest.get(key)
            if first is None:
                by_digest[key] = v
            else:
                replace[id(v)] = first
                del prog.constants[v]
                merged += 1

        seen: dict[tuple, object] = {}
        kept = []
        for op in prog.ops:
            op.inputs = [res(v) for v in op.inputs]
            if op.fn is not None or op.has_effects():
                kept.append(op)
                continue
            sub = _closed_jaxpr_param(op.eqn) if op.eqn is not None else None
            if sub is not None and len(sub.jaxpr.eqns) > _CSE_MAX_SUBJAXPR_EQNS:
                # keying would pretty-print the whole sub-program (a
                # scanned model body) for a merge that never exists
                kept.append(op)
                continue
            key = (op.name, tuple(id(v) for v in op.inputs), op.attr_text())
            prior = seen.get(key)
            if prior is None:
                seen[key] = op
                kept.append(op)
            else:
                for old, new in zip(op.outputs, prior.outputs):
                    replace[id(old)] = new
                merged += 1
        prog.ops = kept
        prog.outputs = [res(v) for v in prog.outputs]
        return PassResult(merged, f"merged={merged}")


def _registry():
    from .fuse import FusionPass
    from .overlap import CollectiveOverlap
    from .patterns import PatternRewriter
    from .shard_prop import ShardingPropagation
    from .shard_search import ShardingSearch
    reg = {
        "dce": DeadCodeElimination,
        "fold": ConstantFolding,
        "cse": CommonSubexprElimination,
        "pattern": PatternRewriter,
        "fuse": FusionPass,
        "shard_search": ShardingSearch,
        "shard_prop": ShardingPropagation,
        "overlap": CollectiveOverlap,
    }
    assert set(reg) == set(PASSES), "pass registry drifted from PASSES"
    return reg


class PassManager:
    """Ordered pass runner, instrumented through the observability
    catalog (pass wall time + edit counts) and span tracing."""

    def __init__(self, passes: Optional[list] = None):
        self.passes = list(passes) if passes is not None else []

    @classmethod
    def default(cls) -> "PassManager":
        """Pipeline from FLAGS_pir_passes (ordered comma list; unknown
        names raise — same closed-registry discipline as fault sites)."""
        from ..framework import flags as _flags
        spec = (_flags.flag_value("pir_passes") or "").strip()
        reg = _registry()
        passes = []
        for name in filter(None, (s.strip() for s in spec.split(","))):
            if name not in reg:
                raise ValueError(f"unknown PIR pass {name!r} in "
                                 f"FLAGS_pir_passes; registered: {sorted(reg)}")
            passes.append(reg[name]())
        return cls(passes)

    def run(self, prog: Program) -> dict:
        """Run all passes in order; returns {pass_name: PassResult} plus
        per-pass seconds in PassResult.notes-adjacent ``report`` dict.

        Under ``FLAGS_pir_verify`` the structural verifier
        (pir/verifier.py) gates the pipeline: mode "on" re-verifies the
        program after every pass (the dead-code rule turns strict right
        after a dce run); mode "boundary" verifies once after the final
        pass. An ``IRVerificationError`` propagates to the caller —
        pipeline.compile_flat catches it and degrades to plain jax.jit
        under ``pir_fallback_total{stage="verify"}``."""
        from ..observability import span as _span
        from ..observability.catalog import metric as _metric
        from .verifier import verify_mode, verify_program
        mode = verify_mode()
        report: dict[str, dict] = {}
        with _span("pir.pipeline", program=prog.name, ops=len(prog.ops)):
            last_name = None
            for p in self.passes:
                t0 = time.perf_counter()
                with _span(f"pir.pass.{p.name}"):
                    result = p.run(prog)
                dt = time.perf_counter() - t0
                _metric("pir_pass_seconds", **{"pass": p.name}).observe(dt)
                if result.edits:
                    _metric("pir_pass_edits_total",
                            **{"pass": p.name}).inc(result.edits)
                report[p.name] = {"seconds": dt, "edits": result.edits,
                                  "notes": result.notes}
                last_name = p.name
                if mode == "on":
                    verify_program(prog, strict_dead=(p.name == "dce"),
                                   where=p.name)
            if mode == "boundary" and last_name is not None:
                verify_program(prog, strict_dead=(last_name == "dce"),
                               where=last_name)
        try:
            from ..observability.recorder import get_recorder
            rec = get_recorder()
            if rec.enabled:
                rec.record("pir_pipeline", program=prog.name,
                           passes=len(self.passes),
                           edits=sum(r["edits"] for r in report.values()))
        except Exception:  # noqa: BLE001 — black box never breaks a compile
            pass
        return report
