"""paddle.version. reference: the build-generated python/paddle/version.py
(full_version, major/minor/patch/rc, commit, cuda()/cudnn() queries)."""

from __future__ import annotations

full_version = "0.1.0"
major, minor, patch = (int(x) for x in full_version.split("."))
rc = 0
commit = "unknown"
istaged = False
with_pip_cuda_libraries = "OFF"

__all__ = ["full_version", "major", "minor", "patch", "rc", "commit",
           "show", "cuda", "cudnn", "nccl", "xpu", "tpu"]


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("accelerator: TPU (XLA)")


def cuda():
    """No CUDA on TPU builds — reference returns 'False' for cpu builds."""
    return "False"


def cudnn():
    return "False"


def nccl():
    return "False"


def xpu():
    return "False"


def tpu():
    import jax
    try:
        d = jax.devices()[0]
        return getattr(d, "device_kind", d.platform)
    except Exception:  # noqa: BLE001
        return "unavailable"
