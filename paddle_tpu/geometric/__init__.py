"""Graph learning ops. reference: python/paddle/geometric/
(message_passing/send_recv.py send_u_recv:25, send_ue_recv, send_uv;
math.py segment_sum/mean/max/min; sampling/neighbors.py sample_neighbors;
reindex.py reindex_graph).

TPU-native: every message-passing op is gather + segment-reduce — XLA lowers
these to efficient one-pass scatters on TPU; no hand-written graph kernels
(reference: paddle/phi/kernels/gpu/graph_send_recv_kernel.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, execute, _unwrap

__all__ = [
    "weighted_sample_neighbors", "reindex_heter_graph","send_u_recv", "send_ue_recv", "send_uv",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "sample_neighbors", "reindex_graph"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed from sum + count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _out_size(out_size, dst_index):
    if out_size is not None:
        return int(out_size)
    if isinstance(dst_index, jax.core.Tracer):
        raise ValueError(
            "out_size is required under jit/to_static tracing — the output "
            "row count cannot be read from a traced index array; pass "
            "out_size=<num_nodes> explicitly")
    return int(np.asarray(jax.device_get(dst_index)).max()) + 1 if dst_index.size else 0


def _segment_reduce(msgs, dst, num, pool_type):
    if pool_type == "mean":
        s = jax.ops.segment_sum(msgs, dst, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst, msgs.dtype), dst,
                                  num_segments=num)
        shape = (num,) + (1,) * (msgs.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1)
    out = _REDUCERS[pool_type](msgs, dst, num_segments=num)
    if pool_type in ("max", "min"):
        # paddle semantics: untouched rows are 0, not +-inf
        touched = jax.ops.segment_sum(jnp.ones_like(dst, jnp.float32), dst,
                                      num_segments=num) > 0
        shape = (num,) + (1,) * (msgs.ndim - 1)
        out = jnp.where(touched.reshape(shape), out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and reduce onto dst. reference:
    python/paddle/geometric/message_passing/send_recv.py:25."""
    reduce_op = reduce_op.lower()
    num = _out_size(out_size, _unwrap(dst_index))

    def f(xv, src, dst):
        return _segment_reduce(xv[src], dst, num, reduce_op)
    return execute(f, x, src_index, dst_index, _name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce onto dst.
    reference: send_recv.py send_ue_recv."""
    message_op = message_op.lower()
    reduce_op = reduce_op.lower()
    num = _out_size(out_size, _unwrap(dst_index))
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(xv, ev, src, dst):
        msgs = combine(xv[src], ev)
        return _segment_reduce(msgs, dst, num, reduce_op)
    return execute(f, x, y, src_index, dst_index, _name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints. reference: send_recv.py send_uv."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op.lower()]

    def f(xv, yv, src, dst):
        return combine(xv[src], yv[dst])
    return execute(f, x, y, src_index, dst_index, _name="send_uv")


def _segment(pool):
    def op(data, segment_ids, name=None):
        seg = jnp.asarray(_unwrap(segment_ids))
        num = int(np.asarray(jax.device_get(seg)).max()) + 1 if seg.size else 0

        def f(d, s):
            return _segment_reduce(d, s, num, pool)
        return execute(f, data, segment_ids, _name=f"segment_{pool}")
    op.__name__ = f"segment_{pool}"
    return op


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling on CSC graphs. reference:
    python/paddle/geometric/sampling/neighbors.py sample_neighbors.
    Host-side (data-dependent shapes are inherently dynamic — the reference
    also runs this on CPU for dataloading)."""
    row_np = np.asarray(jax.device_get(_unwrap(row)))
    colptr_np = np.asarray(jax.device_get(_unwrap(colptr)))
    nodes = np.asarray(jax.device_get(_unwrap(input_nodes)))
    eids_np = (np.asarray(jax.device_get(_unwrap(eids)))
               if eids is not None else None)
    rng = np.random.RandomState()
    out_nbr, out_cnt, out_eids = [], [], []
    for n in nodes.tolist():
        lo, hi = int(colptr_np[n]), int(colptr_np[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            picked = np.arange(lo, hi)
        else:
            picked = lo + rng.choice(deg, sample_size, replace=False)
        out_nbr.append(row_np[picked])
        out_cnt.append(len(picked))
        if eids_np is not None:
            out_eids.append(eids_np[picked])
    neighbors = Tensor(np.concatenate(out_nbr) if out_nbr
                       else np.zeros((0,), row_np.dtype))
    counts = Tensor(np.asarray(out_cnt, np.int32))
    if return_eids:
        if eids_np is None:
            raise ValueError("return_eids=True requires eids")
        return neighbors, counts, Tensor(np.concatenate(out_eids))
    return neighbors, counts


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids. reference:
    python/paddle/geometric/reindex.py reindex_graph."""
    x_np = np.asarray(jax.device_get(_unwrap(x)))
    nbr_np = np.asarray(jax.device_get(_unwrap(neighbors)))
    cnt_np = np.asarray(jax.device_get(_unwrap(count)))
    mapping = {}
    for n in x_np.tolist():
        mapping.setdefault(int(n), len(mapping))
    reindexed = np.empty_like(nbr_np)
    for i, n in enumerate(nbr_np.tolist()):
        reindexed[i] = mapping.setdefault(int(n), len(mapping))
    # edge list: dst repeated by count
    dst = np.repeat(np.arange(len(x_np)), cnt_np)
    keys = np.fromiter(mapping.keys(), dtype=x_np.dtype, count=len(mapping))
    return Tensor(reindexed), Tensor(dst.astype(nbr_np.dtype)), Tensor(keys)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted neighbor sampling (probability proportional to edge
    weight). reference: geometric/sampling/neighbors.py
    weighted_sample_neighbors. Host op (ragged outputs)."""
    r = np.asarray(_unwrap(row))
    cp = np.asarray(_unwrap(colptr))
    wts = np.asarray(_unwrap(edge_weight))
    nodes = np.asarray(_unwrap(input_nodes))
    eid_arr = np.asarray(_unwrap(eids)) if eids is not None else None
    if return_eids and eid_arr is None:
        raise ValueError("return_eids=True requires eids")
    rng = np.random.default_rng(np.random.randint(0, 2 ** 31))
    out_nb, out_cnt, out_eids = [], [], []
    for nd in nodes.tolist():
        beg, end = int(cp[nd]), int(cp[nd + 1])
        idx = np.arange(beg, end)
        w = wts[beg:end].astype(np.float64)
        if sample_size > 0 and len(idx) > sample_size:
            nnz = int((w > 0).sum())
            if nnz == 0:
                # all weights zero: no edge has positive probability, but a
                # sampler that returns nothing starves the caller — fall
                # back to a UNIFORM draw (not the first-k edges)
                idx = rng.choice(idx, size=sample_size, replace=False)
            elif nnz < sample_size:
                # take every positive-weight edge, then fill the remainder
                # uniformly from the zero-weight edges (one policy for both
                # degenerate branches: zero-weight edges are uniform filler)
                order = np.argsort(-w)
                fill = rng.choice(idx[order[nnz:]], size=sample_size - nnz,
                                  replace=False)
                idx = rng.permutation(np.concatenate([idx[order[:nnz]],
                                                      fill]))
            else:
                p = w / w.sum()
                idx = rng.choice(idx, size=sample_size, replace=False, p=p)
        out_nb.extend(r[idx].tolist())
        out_cnt.append(len(idx))
        if return_eids:
            out_eids.extend(eid_arr[idx].tolist())
    res = (Tensor(jnp.asarray(np.asarray(out_nb, np.int64))),
           Tensor(jnp.asarray(np.asarray(out_cnt, np.int64))))
    if return_eids:
        res = res + (Tensor(jnp.asarray(np.asarray(out_eids, np.int64))),)
    return res


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Reindex a heterogeneous graph: same mapping as reindex_graph but
    neighbors/count come per edge type. reference:
    geometric/reindex.py reindex_heter_graph."""
    xs = np.asarray(_unwrap(x))
    nb_list = [np.asarray(_unwrap(nb)) for nb in neighbors]
    ct_list = [np.asarray(_unwrap(ct)) for ct in count]
    uniq = {}
    for v in xs.tolist():
        uniq.setdefault(v, len(uniq))
    for nb in nb_list:
        for v in nb.tolist():
            uniq.setdefault(v, len(uniq))
    re_srcs = [np.asarray([uniq[v] for v in nb.tolist()], np.int64)
               for nb in nb_list]
    re_dsts = [np.repeat(np.arange(len(xs), dtype=np.int64), ct)
               for ct in ct_list]
    nodes = np.asarray(sorted(uniq, key=uniq.get), np.int64)
    return (Tensor(jnp.asarray(np.concatenate(re_srcs))),
            Tensor(jnp.asarray(np.concatenate(re_dsts))),
            Tensor(jnp.asarray(nodes)))
