"""paddle.audio surface. reference: python/paddle/audio/__init__.py
(features, functional, datasets, backends)."""

from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import datasets  # noqa: F401
from . import backends  # noqa: F401

__all__ = ["functional", "features", "datasets", "backends"]
