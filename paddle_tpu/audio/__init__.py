"""paddle.audio surface. reference: python/paddle/audio/__init__.py
(features, functional, datasets, backends)."""

from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import datasets  # noqa: F401
from . import backends  # noqa: F401

__all__ = ["functional", "features", "datasets", "backends"]


# audio file IO over the stdlib wave module (reference: audio/backends —
# soundfile is unavailable in this environment, WAV PCM covers the tests)

def _wav_params(path):
    import wave
    with wave.open(path, "rb") as w:
        return w.getframerate(), w.getnframes(), w.getnchannels(), \
            w.getsampwidth()


class AudioInfo:
    def __init__(self, sample_rate, num_frames, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    """reference: audio/backends info."""
    sr, nf, nc, sw = _wav_params(filepath)
    return AudioInfo(sr, nf, nc, sw * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load a PCM WAV file -> (Tensor (C, L) or (L, C), sample_rate)."""
    import wave
    import numpy as np
    import jax.numpy as jnp
    from ..framework.core import Tensor
    with wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        nc = w.getnchannels()
        sw = w.getsampwidth()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
    if sw == 1:  # WAV 8-bit PCM is UNSIGNED, centered at 128
        data = np.frombuffer(raw, np.uint8).reshape(-1, nc)
        data = data.astype(np.int16) - 128
    else:
        dt = {2: np.int16, 4: np.int32}[sw]
        data = np.frombuffer(raw, dt).reshape(-1, nc)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * sw - 1))
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16):
    """Save a waveform Tensor to PCM WAV."""
    import wave
    import numpy as np
    data = np.asarray(src._data if hasattr(src, "_data") else src)
    if channels_first:
        data = data.T
    if data.dtype.kind == "f":
        scale = float(2 ** (bits_per_sample - 1) - 1)
        data = np.clip(data, -1.0, 1.0) * scale
    if bits_per_sample == 8:  # unsigned on disk
        data = (data + 128).clip(0, 255).astype(np.uint8)
    else:
        dt = {16: np.int16, 32: np.int32}[bits_per_sample]
        data = data.astype(dt)
    with wave.open(filepath, "wb") as w:
        w.setnchannels(data.shape[1] if data.ndim == 2 else 1)
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(data.tobytes())
