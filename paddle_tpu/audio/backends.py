"""Audio IO backends. reference: python/paddle/audio/backends/
(init_backend.py, wave_backend.py) — stdlib wave file IO, no soundfile dep.
"""

from __future__ import annotations

import wave as _wave

import numpy as np

from ..framework.core import Tensor

__all__ = ["list_available_backends", "get_current_backend", "set_backend",
           "load", "save", "info"]

_backend = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return _backend


def set_backend(backend_name):
    global _backend
    if backend_name not in list_available_backends():
        raise NotImplementedError(f"backend {backend_name} not available")
    _backend = backend_name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample,
                 encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """reference: audio/backends/wave_backend.py load."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 3:
        # 24-bit PCM: assemble little-endian triples into int32
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3).astype(np.int32)
        data = (b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16))
        data = np.where(data >= 1 << 23, data - (1 << 24), data).reshape(-1, nch)
    else:
        dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype).reshape(-1, nch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_S",
         bits_per_sample=16):
    data = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if channels_first:
        data = data.T
    if bits_per_sample == 8:
        # 8-bit WAV is offset-binary, matching load()'s (x - 128) / 128
        pcm = np.clip(data * 128.0 + 128.0, 0, 255).astype(np.uint8)
    else:
        # clamp in float64 — float32 cannot represent 2^31 - 1 exactly
        scale = float(2 ** (bits_per_sample - 1))
        pcm = np.clip(data.astype(np.float64) * scale, -scale,
                      scale - 1).astype(
            {16: np.int16, 32: np.int32}[bits_per_sample])
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1] if data.ndim == 2 else 1)
        f.setsampwidth(bits_per_sample // 8)
        f.setframerate(sample_rate)
        f.writeframes(pcm.tobytes())
