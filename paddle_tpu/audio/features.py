"""Audio feature layers. reference: python/paddle/audio/features/layers.py
(Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC).
"""

from __future__ import annotations

from .. import signal as _signal
from ..framework.core import execute
from ..nn.layer.layers import Layer
from . import functional as F

import jax.numpy as jnp

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """reference: audio/features/layers.py Spectrogram."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = F.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                            window=self.window, center=self.center,
                            pad_mode=self.pad_mode)
        return execute(lambda s: jnp.abs(s) ** self.power, spec,
                       _name="spec_power")


class MelSpectrogram(Layer):
    """reference: audio/features/layers.py MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = F.compute_fbank_matrix(sr, n_fft, n_mels, f_min,
                                            f_max, htk, norm, dtype)

    def forward(self, x):
        spec = self.spectrogram(x)          # [..., n_fft//2+1, frames]
        return execute(lambda fb, s: jnp.einsum("mf,...ft->...mt", fb, s),
                       self.fbank, spec, _name="mel_project")


class LogMelSpectrogram(Layer):
    """reference: audio/features/layers.py LogMelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    """reference: audio/features/layers.py MFCC."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db, dtype)
        self.dct = F.create_dct(n_mfcc, n_mels, dtype=dtype)  # [n_mels, n_mfcc]

    def forward(self, x):
        lm = self.logmel(x)                 # [..., n_mels, frames]
        return execute(lambda d, s: jnp.einsum("mk,...mt->...kt", d, s),
                       self.dct, lm, _name="mfcc_dct")
