"""Audio datasets. reference: python/paddle/audio/datasets/{tess.py, esc50.py}.
Synthetic deterministic stand-ins under zero egress (class-dependent tones).
"""

from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["TESS", "ESC50"]


class _SyntheticAudioDataset(Dataset):
    def __init__(self, num_classes, n, sr, duration_s, mode, feat_type="raw",
                 seed=0, **feat_kwargs):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.sample_rate = sr
        self.labels = rng.randint(0, num_classes, n).astype(np.int64)
        t = np.arange(int(sr * duration_s)) / sr
        # per-class fundamental tone + harmonics + noise
        self.waves = np.stack([
            (np.sin(2 * np.pi * (110 * (c + 1)) * t)
             + 0.5 * np.sin(2 * np.pi * (220 * (c + 1)) * t)
             + 0.1 * rng.randn(len(t))).astype(np.float32)
            for c in self.labels])
        self.feat_type = feat_type
        self._feat_layer = None
        if feat_type != "raw":
            from . import features as _feat
            name = {"spectrogram": "Spectrogram",
                    "melspectrogram": "MelSpectrogram",
                    "logmelspectrogram": "LogMelSpectrogram",
                    "mfcc": "MFCC"}[feat_type]
            self._feat_layer = getattr(_feat, name)(sr=sr, **feat_kwargs)

    def _features(self, wave):
        if self._feat_layer is None:
            return wave
        from ..framework.core import to_tensor
        return self._feat_layer(to_tensor(wave[None]))._data[0]

    def __getitem__(self, idx):
        return self._features(self.waves[idx]), self.labels[idx]

    def __len__(self):
        return len(self.labels)


class TESS(_SyntheticAudioDataset):
    """reference: python/paddle/audio/datasets/tess.py (7 emotions)."""

    def __init__(self, mode="train", n_folds=1, split=1, feat_type="raw",
                 archive=None, **kwargs):
        super().__init__(num_classes=7, n=128, sr=24414, duration_s=0.5,
                         mode=mode, feat_type=feat_type, seed=10, **kwargs)


class ESC50(_SyntheticAudioDataset):
    """reference: python/paddle/audio/datasets/esc50.py (50 classes)."""

    def __init__(self, mode="train", split=1, feat_type="raw", archive=None,
                 **kwargs):
        super().__init__(num_classes=50, n=128, sr=44100, duration_s=0.25,
                         mode=mode, feat_type=feat_type, seed=20, **kwargs)
