"""Audio DSP functional ops. reference: python/paddle/audio/functional/
(functional.py: hz_to_mel, mel_to_hz, mel_frequencies, fft_frequencies,
compute_fbank_matrix, power_to_db, create_dct; window.py: get_window).

Pure jnp — everything fuses under jit; window/filterbank construction is
host-side numpy (static, shape-only) exactly once.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, execute

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def hz_to_mel(freq, htk=False):
    """reference: audio/functional/functional.py hz_to_mel."""
    scalar = not isinstance(freq, Tensor)
    f = freq.numpy() if isinstance(freq, Tensor) else np.asarray(freq, np.float32)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                       mel)
    return float(mel) if scalar and mel.ndim == 0 else Tensor(jnp.asarray(mel))


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = mel.numpy() if isinstance(mel, Tensor) else np.asarray(mel, np.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar and hz.ndim == 0 else Tensor(jnp.asarray(hz))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = np.linspace(low, high, n_mels)
    hz = np.asarray([mel_to_hz(float(m), htk) for m in mels], dtype)
    return Tensor(jnp.asarray(hz))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk).numpy(), np.float64)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    weights = np.zeros((n_mels, len(fftfreqs)))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights.astype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0, name=None):
    """reference: audio/functional/functional.py power_to_db."""
    def f(s):
        log_spec = 10.0 * (jnp.log10(jnp.maximum(s, amin))
                           - jnp.log10(jnp.maximum(jnp.asarray(ref_value), amin)))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return execute(f, spect, _name="power_to_db")


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II basis [n_mels, n_mfcc]."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    basis = np.cos(math.pi / n_mels * (n + 0.5) * k)     # [n_mfcc, n_mels]
    if norm == "ortho":
        basis[0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return Tensor(jnp.asarray(basis.T.astype(dtype)))


def _win_np(window, win_length, fftbins=True):
    n = win_length
    if isinstance(window, (tuple,)):
        name, *params = window
    else:
        name, params = window, []
    sym = not fftbins
    m = n + 1 if not sym else n

    def _cosine_sum(coeffs):
        k = np.arange(m)
        w = np.zeros(m)
        for i, c in enumerate(coeffs):
            w += (-1) ** i * c * np.cos(2 * math.pi * i * k / (m - 1) if m > 1 else k * 0)
        return w

    if name in ("hann", "hanning"):
        w = _cosine_sum([0.5, 0.5])
    elif name == "hamming":
        w = _cosine_sum([0.54, 0.46])
    elif name == "blackman":
        w = _cosine_sum([0.42, 0.5, 0.08])
    elif name == "bohman":
        fac = np.abs(np.linspace(-1, 1, m))
        w = (1 - fac) * np.cos(math.pi * fac) + 1.0 / math.pi * np.sin(math.pi * fac)
    elif name == "bartlett":
        w = np.bartlett(m)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = np.kaiser(m, beta)
    elif name == "gaussian":
        std = params[0] if params else 7.0
        k = np.arange(m) - (m - 1) / 2
        w = np.exp(-0.5 * (k / std) ** 2)
    elif name == "exponential":
        tau = params[0] if params else 1.0
        k = np.abs(np.arange(m) - (m - 1) / 2)
        w = np.exp(-k / tau)
    elif name == "triang":
        k = np.arange(1, (m + 1) // 2 + 1)
        if m % 2 == 0:
            w = (2 * k - 1.0) / m
            w = np.concatenate([w, w[::-1]])
        else:
            w = 2 * k / (m + 1.0)
            w = np.concatenate([w, w[-2::-1]])
    elif name == "taylor":
        # 4-term Taylor approximation via chebwin-like cosine sum fallback
        w = _cosine_sum([0.42, 0.5, 0.08])
    elif name in ("boxcar", "rect", "rectangular", "ones"):
        w = np.ones(m)
    else:
        raise ValueError(f"unknown window {window!r}")
    return w[:-1] if not sym and m > n else w


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """reference: python/paddle/audio/functional/window.py get_window."""
    return Tensor(jnp.asarray(_win_np(window, win_length, fftbins).astype(dtype)))
