"""Short-time Fourier transforms. reference: python/paddle/signal.py
(stft, istft).

TPU-native: framing is a gather/strided-reshape that XLA fuses with the FFT;
no frame_kernel / overlap_add CUDA kernels needed (reference:
paddle/phi/kernels/gpu/frame_kernel.cu, overlap_add_kernel.cu).
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.core import execute

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame(a, frame_length, hop_length, axis=-1):
    if axis not in (-1, a.ndim - 1, 0):
        raise ValueError("frame: axis must be 0 or -1")
    seq_last = axis in (-1, a.ndim - 1)
    if not seq_last:
        a = jnp.moveaxis(a, 0, -1)
    n = a.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])  # [F, L]
    out = a[..., idx]                                        # [..., F, L]
    out = jnp.swapaxes(out, -1, -2)                          # [..., L, F]
    if not seq_last:
        out = jnp.moveaxis(out, (-2, -1), (0, 1))
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """reference: python/paddle/signal.py frame()."""
    return execute(lambda a: _frame(a, frame_length, hop_length, axis), x,
                   _name="frame")


def _overlap_add(a, hop_length, axis=-1):
    seq_last = axis in (-1, a.ndim - 1)
    if not seq_last:
        a = jnp.moveaxis(a, (0, 1), (-2, -1))
    *batch, frame_length, num_frames = a.shape
    n = frame_length + hop_length * (num_frames - 1)
    # one scatter-add with the same [F, L] index matrix _frame gathers with
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])   # [F, L]
    frames = jnp.swapaxes(a, -1, -2)                          # [..., F, L]
    out = jnp.zeros((*batch, n), a.dtype).at[..., idx].add(frames)
    if not seq_last:
        out = jnp.moveaxis(out, -1, 0)
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    return execute(lambda a: _overlap_add(a, hop_length, axis), x,
                   _name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """reference: python/paddle/signal.py stft()."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def f(a, w):
        orig_ndim = a.ndim
        if orig_ndim == 1:
            a = a[None]
        if w is None:
            win = jnp.ones((win_length,), a.dtype)
        else:
            win = w
        if win_length < n_fft:
            pad_l = (n_fft - win_length) // 2
            win = jnp.pad(win, (pad_l, n_fft - win_length - pad_l))
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)],
                        mode=pad_mode)
        frames = _frame(a, n_fft, hop_length)      # [..., n_fft, F]
        frames = frames * win[:, None]
        if jnp.iscomplexobj(a) or not onesided:
            spec = jnp.fft.fft(frames, axis=-2)
        else:
            spec = jnp.fft.rfft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.array(n_fft, spec.real.dtype))
        if orig_ndim == 1:
            spec = spec[0]
        return spec

    if window is None:
        return execute(lambda a: f(a, None), x, _name="stft")
    return execute(f, x, window, _name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """reference: python/paddle/signal.py istft()."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if return_complex and onesided:
        raise ValueError(
            "istft: onesided must be False when return_complex is True "
            "(a onesided spectrum reconstructs a real signal)")

    def f(spec, w):
        orig_ndim = spec.ndim
        if orig_ndim == 2:
            spec = spec[None]
        if w is None:
            win = jnp.ones((win_length,), spec.real.dtype)
        else:
            win = w.astype(spec.real.dtype)
        if win_length < n_fft:
            pad_l = (n_fft - win_length) // 2
            win = jnp.pad(win, (pad_l, n_fft - win_length - pad_l))
        if normalized:
            spec = spec * jnp.sqrt(jnp.array(n_fft, spec.real.dtype))
        if onesided and not return_complex:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)  # [..., n_fft, F]
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * win[:, None]
        out = _overlap_add(frames, hop_length)
        # window envelope normalization (NOLA)
        env = _overlap_add(
            jnp.broadcast_to((win * win)[:, None], frames.shape[-2:]),
            hop_length)
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        if orig_ndim == 2:
            out = out[0]
        return out

    if window is None:
        return execute(lambda a: f(a, None), x, _name="istft")
    return execute(f, x, window, _name="istft")
