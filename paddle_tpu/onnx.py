"""paddle.onnx. reference: python/paddle/onnx/export.py (paddle2onnx bridge).

This environment has no onnx/paddle2onnx packages; the portable-program
story on TPU is jit.save's StableHLO artifact (reloadable anywhere XLA
runs). export() converts when onnx tooling is importable, else raises with
that guidance instead of failing obscurely.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise NotImplementedError(
            "onnx is not installed in this environment. For a portable "
            "serialized program use paddle_tpu.jit.save(layer, path, "
            "input_spec=...) — the StableHLO artifact reloads on any XLA "
            "runtime (paddle_tpu.jit.load / inference.Predictor)") from e
    raise NotImplementedError(
        "direct ONNX export is not implemented; export via StableHLO "
        "(jit.save) and convert externally")
