"""paddle.text surface. reference: python/paddle/text/__init__.py —
datasets (Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st)
+ ViterbiDecoder / viterbi_decode (python/paddle/text/viterbi_decode.py).

Datasets are deterministic synthetic stand-ins (zero-egress environment)
with the same shapes/vocab semantics as the reference corpora.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute
from ..io import Dataset
from ..nn.layer.layers import Layer

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "ViterbiDecoder", "viterbi_decode"]


# ---------------------------------------------------------------------------
# viterbi decoding (CRF inference) — lax.scan over time, batched on TPU
# ---------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Find the highest-scoring tag path. reference:
    python/paddle/text/viterbi_decode.py:viterbi_decode, kernel
    paddle/phi/kernels/cpu/viterbi_decode_kernel.cc.

    potentials: [B, T, N] unary emissions; transition_params: [N, N];
    lengths: [B] int64. Returns (scores [B], paths [B, T_max_len]).
    """
    def f(emis, trans, lens):
        B, T, N = emis.shape
        if include_bos_eos_tag:
            # reference semantics: tag N-2 is BOS, N-1 is EOS. Paths start
            # from BOS's transitions and may never land on BOS/EOS.
            bos_mask = jnp.full((N,), -1e4).at[:N - 2].set(0.0)
            start = emis[:, 0] + trans[N - 2][None, :] + bos_mask[None, :]
        else:
            start = emis[:, 0]

        def step(carry, t):
            alpha, history_dummy = carry
            # score[b, i, j] = alpha[b, i] + trans[i, j] + emis[b, t, j]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)               # [B, N]
            best_score = jnp.max(scores, axis=1) + emis[:, t]    # [B, N]
            # mask out steps past each sequence's length
            active = (t < lens)[:, None]
            new_alpha = jnp.where(active, best_score, alpha)
            bp = jnp.where(active, best_prev,
                           jnp.broadcast_to(jnp.arange(N)[None, :], (B, N)))
            return (new_alpha, history_dummy), bp

        (alpha, _), backptrs = jax.lax.scan(
            step, (start, 0), jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, N - 1][None, :]
        last_tag = jnp.argmax(alpha, axis=1)                      # [B]
        score = jnp.max(alpha, axis=1)

        def backtrack(carry, bp_t):
            tag = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # scanning reversed backpointers emits tags T-1..1; the final carry
        # is the tag at time 0
        tag0, path_rev = jax.lax.scan(backtrack, last_tag, backptrs[::-1])
        paths = jnp.concatenate([tag0[:, None], path_rev[::-1].T],
                                axis=1)                           # [B, T]
        return score, paths.astype(jnp.int64 if jax.config.jax_enable_x64
                                   else jnp.int32)

    return execute(f, potentials, transition_params, lengths,
                   _name="viterbi_decode")


class ViterbiDecoder(Layer):
    """reference: python/paddle/text/viterbi_decode.py:ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

class Imdb(Dataset):
    """reference: python/paddle/text/datasets/imdb.py (binary sentiment)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n, vocab, seqlen = 512, 5000, 100
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # class-dependent token distribution so models can learn
        self.docs = [
            rng.randint(lbl * vocab // 4, vocab // 2 + lbl * vocab // 4,
                        seqlen).astype(np.int64)
            for lbl in self.labels]

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Imikolov(Dataset):
    """reference: python/paddle/text/datasets/imikolov.py (n-gram LM)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n, vocab = 1024, 2000
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        self.window_size = window_size
        self.data = rng.randint(0, vocab, (n, window_size)).astype(np.int64)

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """reference: python/paddle/text/datasets/movielens.py."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        rng = np.random.RandomState(rand_seed + (0 if mode == "train" else 1))
        n = 1024
        self.user_ids = rng.randint(0, 943, n).astype(np.int64)
        self.movie_ids = rng.randint(0, 1682, n).astype(np.int64)
        self.ratings = rng.randint(1, 6, n).astype(np.float32)

    def __getitem__(self, idx):
        return (self.user_ids[idx], self.movie_ids[idx], self.ratings[idx])

    def __len__(self):
        return len(self.ratings)


class UCIHousing(Dataset):
    """reference: python/paddle/text/datasets/uci_housing.py (13-feat regression)."""

    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(4 if mode == "train" else 5)
        n = 404 if mode == "train" else 102
        w = np.random.RandomState(99).randn(13).astype(np.float32)
        self.features = rng.randn(n, 13).astype(np.float32)
        self.prices = (self.features @ w + 22.5
                       + 0.5 * rng.randn(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.prices)


class _SyntheticTranslation(Dataset):
    def __init__(self, mode, dict_size, seed):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        n, seqlen = 512, 20
        self.dict_size = max(dict_size, 100)
        self.src = rng.randint(3, self.dict_size, (n, seqlen)).astype(np.int64)
        # toy task: target = source shifted by one vocab id
        self.trg = np.minimum(self.src + 1, self.dict_size - 1)

    def __getitem__(self, idx):
        src = self.src[idx]
        trg = self.trg[idx]
        return src, trg[:-1], trg[1:]

    def __len__(self):
        return len(self.src)


class WMT14(_SyntheticTranslation):
    """reference: python/paddle/text/datasets/wmt14.py."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        super().__init__(mode, dict_size, seed=6)


class WMT16(_SyntheticTranslation):
    """reference: python/paddle/text/datasets/wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(mode, src_dict_size, seed=8)


class Conll05st(Dataset):
    """reference: python/paddle/text/datasets/conll05.py (SRL)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 mode="train"):
        rng = np.random.RandomState(12 if mode == "train" else 13)
        n, seqlen = 256, 30
        self.word_vocab, self.label_vocab = 5000, 67
        self.words = rng.randint(0, self.word_vocab, (n, seqlen)).astype(np.int64)
        self.predicates = rng.randint(0, 3000, (n,)).astype(np.int64)
        self.labels = rng.randint(0, self.label_vocab, (n, seqlen)).astype(np.int64)

    def get_dict(self):
        return ({f"w{i}": i for i in range(self.word_vocab)},
                {f"v{i}": i for i in range(3000)},
                {f"l{i}": i for i in range(self.label_vocab)})

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return len(self.words)
