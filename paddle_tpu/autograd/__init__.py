"""Autograd surface: backward, grad, PyLayer, functional jvp/vjp/hessian.

reference: python/paddle/autograd/ — backward_mode.py, py_layer.py,
autograd.py. The engine itself lives in framework/core.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import (Tensor, _run_backward, execute, no_grad,
                              is_grad_enabled, set_grad_enabled, enable_grad)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad", "saved_tensors_hooks",
           "enable_grad", "set_grad_enabled", "is_grad_enabled", "jvp", "vjp",
           "hessian", "jacobian"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: python/paddle/autograd/backward_mode.py)."""
    _run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None):
    """paddle.grad (reference: python/paddle/base/dygraph/base.py:grad,
    engine GeneralGrad in paddle/fluid/eager/backward.cc)."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    capture = {id(t): t for t in ins}
    captured = _run_backward(outs, grad_outputs, retain_graph=retain_graph,
                             capture=capture, create_graph=create_graph)
    results = []
    for t in ins:
        g = (captured or {}).get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; pass "
                    "allow_unused=True to return None for it")
            results.append(None)
        else:
            results.append(Tensor(g) if not isinstance(g, Tensor) else g)
    return results


# ---------------------------------------------------------------------------
# PyLayer: custom autograd (reference: python/paddle/autograd/py_layer.py)
# ---------------------------------------------------------------------------


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        hooks = saved_tensors_hooks._active
        if hooks is not None:
            self._saved = tuple(hooks[0](t) for t in tensors)
            # capture the matching unpack NOW: backward usually runs after
            # the with-block exits, when _active is gone
            self._unpack = hooks[1]
        else:
            self._saved = tensors
            self._unpack = None

    def saved_tensor(self):
        """reference contract (autograd/py_layer.py:105): a METHOD returning
        the tensors stored by save_for_backward."""
        unpack = getattr(self, "_unpack", None)
        if unpack is not None:
            return tuple(unpack(t) for t in self._saved)
        return self._saved

    def saved_tensors(self):
        return self.saved_tensor()

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def set_materialize_grads(self, value):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom forward/backward. The backward is spliced into the tape as a
    Node whose 'vjp' calls the user's backward — same role as
    egr::PyLayerGradNode (reference: paddle/fluid/eager/pylayer/)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework import core as _core

        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        if not _core.grad_enabled():
            return out

        tensor_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if not tensor_inputs:
            return out

        multi = isinstance(out, (list, tuple))
        out_list = list(out) if multi else [out]
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]

        import weakref

        def align_grads(res, wrap):
            """paddle semantics: backward returns one grad per Tensor input
            of forward, in order; keep only those recorded as
            differentiable. `wrap` fixes the output flavor (raw array for
            the vjp tape, live Tensor for create_graph)."""
            if not isinstance(res, (list, tuple)):
                res = (res,)
            res_iter = iter(res)
            flat = []
            for a in args:
                if not isinstance(a, Tensor):
                    continue
                r = next(res_iter, None)
                if a.stop_gradient:
                    continue
                flat.append(wrap(r, a))
            return tuple(flat)

        def vjp_fn(cot_tree):
            cots = cot_tree if isinstance(cot_tree, (list, tuple)) else [cot_tree]
            grads_in = [Tensor(c) for c in cots]
            res = cls.backward(ctx, *grads_in)
            return align_grads(res, lambda r, a: (
                r._data if isinstance(r, Tensor)
                else jnp.zeros_like(a._data) if r is None
                else jnp.asarray(r)))

        def tape_vjp_fn(cot_tensors):
            # create_graph: run the user's backward on LIVE tape tensors so
            # its ops are recorded; grads w.r.t. the primal inputs flow
            # through ctx.save_for_backward'ed tensors (saved by identity)
            res = cls.backward(ctx, *cot_tensors)
            return align_grads(res, lambda r, a: (
                r if isinstance(r, Tensor)
                else Tensor(jnp.zeros_like(a._data)) if r is None
                else Tensor(jnp.asarray(r))))

        new_outs = [Tensor(o._data, stop_gradient=False) for o in out_tensors]
        import jax.tree_util as jtu
        treedef = jtu.tree_structure(tuple(range(len(new_outs))))
        node = _core.Node("PyLayer:" + cls.__name__, vjp_fn, tensor_inputs,
                          new_outs, treedef)
        node.tape_vjp_fn = tape_vjp_fn
        for t in new_outs:
            t._node = node

        it = iter(new_outs)
        result = [next(it) if isinstance(o, Tensor) else o for o in out_list]
        return result if multi else result[0]


class PyLayerContext_:
    pass


# ---------------------------------------------------------------------------
# functional transforms (reference: python/paddle/autograd/autograd.py,
# incubate/autograd/functional.py) — direct jax mappings
# ---------------------------------------------------------------------------


def _to_pure(func):
    def pure(*arrs):
        ts = [Tensor(a, stop_gradient=True) for a in arrs]
        with no_grad():
            out = func(*ts)
        return jax.tree_util.tree_map(
            lambda o: o._data if isinstance(o, Tensor) else o, out)
    return pure


def vjp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._data for x in xs_l]
    out, vjp_fn = jax.vjp(_to_pure(func), *arrs)
    if v is None:
        v_arr = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t,
            v if not isinstance(v, (list, tuple)) or len(v) > 1 else v[0])
    grads = vjp_fn(v_arr)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    return wrap(out), [Tensor(g) for g in grads] if len(grads) > 1 else Tensor(grads[0])


def jvp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._data for x in xs_l]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrs]
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        tangents = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in v_l]
    out, tang = jax.jvp(_to_pure(func), tuple(arrs), tuple(tangents))
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    return wrap(out), wrap(tang)


def _tape_jacobian(ys, xs, batch_axis=None):
    """reference contract (autograd/autograd.py:461): jacobian(ys, xs) with
    COMPUTED output tensors — rows via repeated tape backward passes."""
    _grad = grad
    ys_l = ys if isinstance(ys, (list, tuple)) else [ys]
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    flat = ys_l[0].flatten() if len(ys_l) == 1 else None
    if flat is None:
        raise NotImplementedError("tensor-mode jacobian: single ys only")
    rows = []
    m = int(jnp.prod(jnp.asarray(flat.shape))) if flat.shape else 1
    for i in range(m):
        gs = _grad([flat[i]], list(xs_l), retain_graph=True,
                   allow_unused=True)
        rows.append([jnp.zeros_like(x._data).ravel() if g is None
                     else g._data.ravel() for g, x in zip(gs, xs_l)])
    outs = [Tensor(jnp.stack([r[j] for r in rows]))
            for j in range(len(xs_l))]
    if not isinstance(xs, (list, tuple)):
        return outs[0]
    return outs


def jacobian(func, xs, batch_axis=None):
    if not callable(func):
        # reference signature: first arg is ys (a computed Tensor), not a fn
        return _tape_jacobian(func, xs, batch_axis)
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._data for x in xs_l]
    jac = jax.jacrev(_to_pure(func), argnums=tuple(range(len(arrs))))(*arrs)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    if not isinstance(xs, (list, tuple)):
        return wrap(jac[0] if isinstance(jac, tuple) else jac)
    return wrap(jac)


def hessian(func, xs, batch_axis=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._data for x in xs_l]
    hes = jax.hessian(_to_pure(func), argnums=tuple(range(len(arrs))))(*arrs)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    if not isinstance(xs, (list, tuple)):
        h = hes[0] if isinstance(hes, tuple) else hes
        h = h[0] if isinstance(h, tuple) else h
        return wrap(h)
    return wrap(hes)


class saved_tensors_hooks:
    """reference: autograd/saved_tensors_hooks.py — customize how PyLayer
    saves activations (pack on save, unpack on use; enables host offload).

    Scope note: the eager tape stores op residuals inside XLA-owned vjp
    closures, so these hooks apply to PyLayer's explicitly saved tensors
    (ctx.save_for_backward), same API as the reference."""

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = saved_tensors_hooks._active
        saved_tensors_hooks._active = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = self._prev
        return False
