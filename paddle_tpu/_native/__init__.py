"""Native (C++) runtime bindings, loaded via ctypes.

The reference implements its runtime plumbing in C++ (TCPStore:
paddle/phi/core/distributed/store/tcp_store.h, data feed:
paddle/fluid/framework/data_feed.cc). Here the equivalents live in
/native/*.cc, compiled on first import with g++ (no pybind11 in this image —
ctypes is the binding layer; it also releases the GIL for the duration of
every native call, which is exactly what the collate path wants).

Build artifacts are cached next to this file keyed on a source hash; if the
toolchain is unavailable the package degrades to pure-Python fallbacks
(available = False) without breaking any public API.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")

_SOURCES = ["tcp_store.cc", "collate.cc", "ps_table.cc"]

available = False
_lib = None


def _source_hash():
    h = hashlib.sha256()
    for s in _SOURCES:
        with open(os.path.join(_SRC_DIR, s), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build():
    tag = _source_hash()
    so_path = os.path.join(_HERE, f"libpaddle_tpu_native_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # stale artifacts from older source versions
    for f in os.listdir(_HERE):
        if f.startswith("libpaddle_tpu_native_") and f.endswith(".so"):
            try:
                os.remove(os.path.join(_HERE, f))
            except OSError:
                pass
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    with tempfile.TemporaryDirectory() as td:
        tmp_so = os.path.join(td, "out.so")
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
               "-o", tmp_so] + srcs
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp_so, so_path)
    return so_path


def _bind(lib):
    c = ctypes
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int]
    lib.pt_store_server_port.restype = c.c_int
    lib.pt_store_server_port.argtypes = [c.c_void_p]
    lib.pt_store_server_stop.argtypes = [c.c_void_p]
    lib.pt_store_client_new.restype = c.c_void_p
    lib.pt_store_client_new.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_store_client_free.argtypes = [c.c_void_p]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_int64]
    lib.pt_store_get.restype = c.POINTER(c.c_uint8)
    lib.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(c.c_int64)]
    lib.pt_buffer_free.argtypes = [c.c_void_p]
    lib.pt_store_add.restype = c.c_int
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(c.c_int64)]
    lib.pt_store_wait.restype = c.c_int
    lib.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pt_store_delete.restype = c.c_int
    lib.pt_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_check.restype = c.c_int
    lib.pt_store_check.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_num_keys.restype = c.c_int64
    lib.pt_store_num_keys.argtypes = [c.c_void_p]
    lib.pt_collate_stack.argtypes = [c.POINTER(c.c_void_p), c.c_int64,
                                     c.c_int64, c.c_void_p, c.c_int]
    lib.pt_collate_image_norm.argtypes = [
        c.POINTER(c.POINTER(c.c_uint8)), c.c_int64, c.c_int64, c.c_int64,
        c.c_int64, c.POINTER(c.c_float), c.POINTER(c.c_float),
        c.POINTER(c.c_float), c.c_int]
    # sparse parameter-server table (native/ps_table.cc)
    u64p = c.POINTER(c.c_uint64)
    f32p = c.POINTER(c.c_float)
    lib.pt_ps_table_new.restype = c.c_void_p
    lib.pt_ps_table_new.argtypes = [c.c_int, c.c_int, c.c_float, c.c_float,
                                    c.c_float, c.c_float, c.c_float]
    lib.pt_ps_table_free.argtypes = [c.c_void_p]
    lib.pt_ps_table_pull.argtypes = [c.c_void_p, u64p, c.c_int64, f32p,
                                     c.c_int]
    lib.pt_ps_table_push.argtypes = [c.c_void_p, u64p, c.c_int64, f32p]
    lib.pt_ps_table_merge.argtypes = [c.c_void_p, u64p, c.c_int64, f32p]
    lib.pt_ps_table_assign.argtypes = [c.c_void_p, u64p, c.c_int64, f32p]
    lib.pt_ps_table_size.restype = c.c_int64
    lib.pt_ps_table_size.argtypes = [c.c_void_p]
    lib.pt_ps_table_contains.argtypes = [c.c_void_p, u64p, c.c_int64,
                                         c.POINTER(c.c_uint8)]
    lib.pt_ps_table_keys.restype = c.c_int64
    lib.pt_ps_table_keys.argtypes = [c.c_void_p, u64p, c.c_int64]
    lib.pt_ps_table_add_show_click.argtypes = [c.c_void_p, u64p, c.c_int64,
                                               f32p, f32p]
    lib.pt_ps_table_decay.argtypes = [c.c_void_p, c.c_float]
    lib.pt_ps_table_shrink.restype = c.c_int64
    lib.pt_ps_table_shrink.argtypes = [c.c_void_p, c.c_float, c.c_float]
    lib.pt_ps_table_save.restype = c.c_int
    lib.pt_ps_table_save.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_ps_table_load.restype = c.c_int
    lib.pt_ps_table_load.argtypes = [c.c_void_p, c.c_char_p]
    return lib


try:
    _lib = _bind(ctypes.CDLL(_build()))
    available = True
except Exception as _e:  # noqa: BLE001 — any failure degrades to pure Python
    _build_error = _e
    available = False


def lib():
    if not available:
        raise RuntimeError(f"native runtime unavailable: {_build_error}")
    return _lib


# ---------------------------------------------------------------------------
# high-level helpers
# ---------------------------------------------------------------------------

def collate_stack(arrays, out=None):
    """Stack equal-shaped contiguous numpy arrays into one batch array using
    C++ threads (GIL released). Falls back to np.stack when unavailable."""
    import numpy as np
    if not available or len(arrays) < 2:
        return np.stack(arrays)
    if any(getattr(a, "dtype", None) != arrays[0].dtype for a in arrays):
        return np.stack(arrays)  # mixed dtypes: keep numpy promotion rules
    a0 = np.ascontiguousarray(arrays[0])
    n = len(arrays)
    if out is None:
        out = np.empty((n,) + a0.shape, a0.dtype)
    srcs = (ctypes.c_void_p * n)()
    holders = []
    for i, a in enumerate(arrays):
        ac = np.ascontiguousarray(a, dtype=a0.dtype)
        if ac.shape != a0.shape:
            return np.stack(arrays)
        holders.append(ac)
        srcs[i] = ac.ctypes.data_as(ctypes.c_void_p)
    _lib.pt_collate_stack(srcs, n, a0.nbytes,
                          out.ctypes.data_as(ctypes.c_void_p), 0)
    return out


def collate_image_norm(images, mean, std):
    """Fused uint8 HWC -> normalized float32 CHW batch (vision hot path)."""
    import numpy as np
    imgs = [np.ascontiguousarray(im, dtype=np.uint8) for im in images]
    n = len(imgs)
    h, w, c = imgs[0].shape
    mean = np.asarray(mean, np.float32).reshape(-1)
    std = np.asarray(std, np.float32).reshape(-1)
    if mean.size == 1:
        mean = np.repeat(mean, c)
    if std.size == 1:
        std = np.repeat(std, c)
    out = np.empty((n, c, h, w), np.float32)
    if not available:
        stacked = np.stack(imgs).astype(np.float32) / 255.0
        stacked = (stacked - mean) / std
        return stacked.transpose(0, 3, 1, 2).copy()
    srcs = (ctypes.POINTER(ctypes.c_uint8) * n)()
    for i, im in enumerate(imgs):
        srcs[i] = im.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    _lib.pt_collate_image_norm(
        srcs, n, h, w, c,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 0)
    return out
