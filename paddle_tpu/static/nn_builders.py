"""paddle.static.nn — static-graph layer builders.

reference: python/paddle/static/nn/__init__.py (30 symbols; builders defined
in static/nn/common.py — fc:30, conv2d, batch_norm, embedding, nce, ... —
plus control_flow.py case/switch_case and static_pylayer.py).

TPU-native: the reference's builders append ops + fresh parameters to the
global Program. Here the program IS the traced jaxpr, so each builder is a
define-and-run call: it creates the parameters (respecting
param_attr/bias_attr via nn.Layer.create_parameter) and applies the op
immediately. Under jit.to_static the call is traced like any eager code.

Parameter persistence mirrors the reference's Program-owned parameters:
every builder draws its parameter names from an explicit `name` argument or
`utils.unique_name.generate`, and stores the created tensors in a
module-level registry. A repeated call with the same resolved name (e.g. an
explicitly named fc, or an unnamed one rebuilt under
`utils.unique_name.guard()`) REUSES the registered parameters instead of
drawing fresh weights, and `static.default_main_program().all_parameters()`
exposes them for optimizers / state_dict — matching how the reference keeps
builder parameters alive on the Program (static/nn/common.py fc:30).
Unnamed calls outside a guard get a fresh unique name each call and thus
fresh parameters, exactly like appending a second fc to a reference
Program.

LoD sequence ops (sequence_conv/pool/expand/softmax/first/last_step),
sparse_embedding and nce serve the legacy LoD/parameter-server pipeline —
descoped on TPU (DESIGN.md ledger) with guided errors.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, execute
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from ..utils import unique_name


def _act(out, activation):
    if activation is None:
        return out
    fn = getattr(F, activation, None)
    if fn is None:
        raise ValueError(f"unknown activation {activation!r}")
    return fn(out)


#: resolved parameter name -> Tensor. The static-graph analog of the
#: reference Program's parameter list; cleared by static.reset_parameters().
#: Like the reference Program, it ACCUMULATES: every unnamed builder call
#: appends fresh parameters (a build loop grows it exactly as it would grow
#: a reference Program) — rebuild under utils.unique_name.guard() to reuse,
#: or reset_parameters() for a fresh program.
#: static.program_guard(main_program=p) swaps in p's own registry, so
#: separate Programs keep separate parameter sets.
_param_registry: dict[str, Tensor] = {}


def reset_parameters():
    """Forget all builder-created parameters (reference analog: a fresh
    Program)."""
    _param_registry.clear()


class _ParamFactory(Layer):
    """Named parameter source for one builder call: reuses nn's
    initializer / weight-attr machinery, but registers every created
    tensor under `<base>.<suffix>` so later calls with the same resolved
    base name reuse it."""

    def __init__(self, kind, name=None):
        super().__init__()
        self._base = name if name else unique_name.generate(kind)
        self._n_w = 0
        self._n_b = 0

    def make(self, shape, attr=None, is_bias=False, default=None,
             dtype=None):
        # ParamAttr(name=...) is the reference's weight-sharing handle:
        # it overrides the positional key so two builders naming the same
        # attr share one parameter (base/param_attr.py)
        attr_name = getattr(attr, "name", None)
        if attr_name:
            key = attr_name
        elif is_bias:
            key = f"{self._base}.b_{self._n_b}"
            self._n_b += 1
        else:
            key = f"{self._base}.w_{self._n_w}"
            self._n_w += 1
        shape = tuple(int(s) for s in shape)
        hit = _param_registry.get(key)
        if hit is not None:
            if tuple(hit.shape) != shape:
                raise ValueError(
                    f"static.nn parameter {key!r} already exists with shape "
                    f"{tuple(hit.shape)}, requested {shape}; pass a "
                    "different name= or call static.nn.reset_parameters()")
            return hit
        p = self.create_parameter(
            shape, attr=attr, dtype=dtype, is_bias=is_bias,
            default_initializer=default)
        if p is None:  # attr=False: caller asked for no parameter
            return None
        p.name = key
        _param_registry[key] = p
        return p

    def buffer(self, key_suffix, value, explicit_name=None):
        """Non-trainable persistent state (batch_norm moving stats)."""
        key = explicit_name or f"{self._base}.{key_suffix}"
        hit = _param_registry.get(key)
        if hit is not None:
            if tuple(hit.shape) != tuple(value.shape):
                raise ValueError(
                    f"static.nn buffer {key!r} already exists with shape "
                    f"{tuple(hit.shape)}, requested {tuple(value.shape)}; "
                    "pass a different name or call "
                    "static.nn.reset_parameters()")
            return hit
        t = Tensor(value, stop_gradient=True)
        t.name = key
        _param_registry[key] = t
        return t


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py fc — flatten trailing dims, linear,
    optional activation."""
    pf = _ParamFactory("static_fc", name)
    xs = tuple(x.shape)
    if num_flatten_dims < 0:
        num_flatten_dims = len(xs) + num_flatten_dims
    in_features = 1
    for d in xs[num_flatten_dims:]:
        in_features *= int(d)
    w = pf.make((in_features, size), attr=weight_attr)
    b = pf.make((size,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None

    def f(a, wt, *bias):
        flat = a.reshape(a.shape[:num_flatten_dims] + (in_features,))
        out = flat @ wt
        if bias:
            out = out + bias[0]
        return out

    args = (x, w) + ((b,) if b is not None else ())
    return _act(execute(f, *args, _name="static_fc"), activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """reference: static/nn/common.py embedding."""
    pf = _ParamFactory("static_embedding", name)
    w = pf.make(tuple(size), attr=param_attr, dtype=dtype)
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """reference: static/nn/common.py conv2d."""
    pf = _ParamFactory("static_conv2d", name)
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = int(input.shape[1 if data_format == "NCHW" else -1])
    w = pf.make((num_filters, cin // groups) + tuple(ks), attr=param_attr)
    b = pf.make((num_filters,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    pf = _ParamFactory("static_conv3d", name)
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    cin = int(input.shape[1 if data_format == "NCDHW" else -1])
    w = pf.make((num_filters, cin // groups) + tuple(ks), attr=param_attr)
    b = pf.make((num_filters,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    return _act(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    pf = _ParamFactory("static_conv2d_transpose", name)
    if filter_size is None:
        raise ValueError("filter_size is required (output_size-only "
                         "inference is not supported)")
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = int(input.shape[1 if data_format == "NCHW" else -1])
    w = pf.make((cin, num_filters // groups) + tuple(ks), attr=param_attr)
    b = pf.make((num_filters,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv2d_transpose(input, w, bias=b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    pf = _ParamFactory("static_conv3d_transpose", name)
    if filter_size is None:
        raise ValueError("filter_size is required")
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    cin = int(input.shape[1 if data_format == "NCDHW" else -1])
    w = pf.make((cin, num_filters // groups) + tuple(ks), attr=param_attr)
    b = pf.make((num_filters,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv3d_transpose(input, w, bias=b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return _act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """reference: static/nn/common.py deformable_conv — delegates to the
    vision op (modulated when mask is given)."""
    from ..vision.ops import deform_conv2d as _dc
    pf = _ParamFactory("static_deform_conv2d", name)
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = int(input.shape[1])
    w = pf.make((num_filters, cin // groups) + tuple(ks), attr=param_attr)
    b = pf.make((num_filters,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    return _dc(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """reference: static/nn/common.py batch_norm. Creates scale/bias +
    moving stats and applies the normalization in one call."""
    pf = _ParamFactory("static_batch_norm", name)
    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    scale = pf.make((c,), attr=param_attr, default=I.Constant(1.0))
    bias = pf.make((c,), attr=bias_attr, is_bias=True)
    mean = pf.buffer("moving_mean", jnp.zeros((c,), jnp.float32),
                     explicit_name=moving_mean_name)
    var = pf.buffer("moving_variance", jnp.ones((c,), jnp.float32),
                    explicit_name=moving_variance_name)
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=not (is_test or use_global_stats),
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    pf = _ParamFactory("static_layer_norm", name)
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    w = pf.make(shape, attr=param_attr, default=I.Constant(1.0)) \
        if scale else None
    b = pf.make(shape, attr=bias_attr, is_bias=True) if shift else None
    out = F.layer_norm(input, shape, weight=w, bias=b, epsilon=epsilon)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    pf = _ParamFactory("static_group_norm", name)
    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    w = pf.make((c,), attr=param_attr, default=I.Constant(1.0))
    b = pf.make((c,), attr=bias_attr, is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    return _act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    pf = _ParamFactory("static_instance_norm", name)
    c = int(input.shape[1])
    w = pf.make((c,), attr=param_attr, default=I.Constant(1.0)) \
        if param_attr is not False else None
    b = pf.make((c,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """reference: static/nn/common.py data_norm — normalization by batch
    statistics, with a learned per-feature affine when
    enable_scale_and_shift is set (reference creates scale_w/bias then)."""
    if enable_scale_and_shift:
        pf = _ParamFactory("static_data_norm", name)
        c = int(input.shape[-1])
        scale_w = pf.make((c,), attr=param_attr, default=I.Constant(1.0))
        bias = pf.make((c,), is_bias=True)

        def f(a, sw, b):
            mean = jnp.mean(a, axis=0, keepdims=True)
            var = jnp.var(a, axis=0, keepdims=True)
            return (a - mean) / jnp.sqrt(var + epsilon) * sw + b

        out = execute(f, input, scale_w, bias, _name="data_norm")
    else:
        def f(a):
            mean = jnp.mean(a, axis=0, keepdims=True)
            var = jnp.var(a, axis=0, keepdims=True)
            return (a - mean) / jnp.sqrt(var + epsilon)

        out = execute(f, input, _name="data_norm")
    return _act(out, act)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: static/nn/common.py prelu — modes all/channel/element."""
    pf = _ParamFactory("static_prelu", name)
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[1 if data_format == "NCHW" else -1]),)
    elif mode == "element":
        shape = tuple(int(s) for s in x.shape[1:])
    else:
        raise ValueError(f"prelu mode must be all/channel/element, got {mode}")
    w = pf.make(shape, attr=param_attr, default=I.Constant(0.25))
    if mode == "channel":
        return F.prelu(x, w, data_format=data_format)
    if mode == "element":
        def f(a, wt):
            return jnp.where(a > 0, a, a * wt[None])  # (1, *x.shape[1:])
        return execute(f, x, w, _name="static_prelu")
    return F.prelu(x, w)  # mode == "all": scalar weight broadcasts


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: static/nn/common.py bilinear_tensor_product:
    out_k = x W_k y^T + b."""
    pf = _ParamFactory("static_bilinear_tensor_product", name)
    dx, dy = int(x.shape[1]), int(y.shape[1])
    w = pf.make((size, dx, dy), attr=param_attr)
    b = pf.make((size,), attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None

    def f(a, c, wt, *bias):
        out = jnp.einsum("bi,kij,bj->bk", a, wt, c)
        if bias:
            out = out + bias[0]
        return out

    args = (x, y, w) + ((b,) if b is not None else ())
    return _act(execute(f, *args, _name="bilinear_tensor_product"), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: static/nn/common.py spectral_norm — normalize a weight by
    its largest singular value via power iteration (stateless: iterations
    run from a fixed start each call, matching the functional contract)."""
    def f(w):
        import jax
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / (wm.shape[0] ** 0.5)
        for _ in range(max(power_iters, 1)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma
    return execute(f, weight, _name="spectral_norm")


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """reference: static/nn/common.py row_conv (lookahead convolution,
    Deep Speech 2): out[t] = sum_{i=0..k} in[t+i] * w[i]."""
    pf = _ParamFactory("static_row_conv", name)
    k = future_context_size
    d = int(input.shape[-1])
    w = pf.make((k + 1, d), attr=param_attr)

    def f(a, wt):
        outs = jnp.zeros_like(a)
        T = a.shape[1]
        for i in range(k + 1):
            seg = a[:, i:, :]
            outs = outs.at[:, :T - i, :].add(seg * wt[i])
        return outs

    return _act(execute(f, input, w, _name="row_conv"), act)


# -- control flow -----------------------------------------------------------

def case(pred_fn_pairs, default=None, name=None):
    """reference: static/nn/control_flow.py case — first true predicate
    wins; chained lax.cond under trace."""
    from . import cond as _cond
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")

    def build(pairs):
        (pred, fn) = pairs[0]
        rest = pairs[1:]
        if not rest:
            if default is None:
                return fn()
            return _cond(pred, fn, default)
        return _cond(pred, fn, lambda: build(rest))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: static/nn/control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    import jax

    def f(idx):
        fns = [fn for _, fn in items]
        keys = jnp.asarray([k for k, _ in items])
        pos = jnp.argmax(keys == idx)
        valid = jnp.any(keys == idx)
        branches = [lambda _, fn=fn: _untensor(fn()) for fn in fns]
        if default is not None:
            branches.append(lambda _: _untensor(default()))
            pos = jnp.where(valid, pos, len(fns))
        else:
            # reference contract: no match and no default -> the branch
            # with the MAX key runs (control_flow.py switch_case docs)
            pos = jnp.where(valid, pos, len(fns) - 1)
        return jax.lax.switch(pos, branches, None)

    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else jnp.asarray(branch_index)
    return Tensor(f(idx))


def _untensor(v):
    return v._data if isinstance(v, Tensor) else jnp.asarray(v)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func — run arbitrary python in the
    graph. Under trace this uses jax.pure_callback with the declared `out`
    shape/dtype; eagerly it just calls func."""
    import jax
    import numpy as np
    xs = x if isinstance(x, (list, tuple)) else [x]
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), _np_dtype(o)) for o in outs]

    def host(*a):
        r = func(*[np.asarray(v) for v in a])
        rs = r if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(v) for v in rs)

    res = jax.pure_callback(host, tuple(specs), *arrs)
    res = [Tensor(r) for r in res]
    return res if isinstance(out, (list, tuple)) else res[0]


def _np_dtype(t):
    import numpy as np
    from ..framework import dtypes as _dt
    d = t.dtype if hasattr(t, "dtype") else t
    try:
        return np.dtype(_dt.convert_dtype(d))
    except Exception:
        return np.dtype(str(d))


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """reference: static/nn/static_pylayer.py — custom fwd/bwd pair in a
    static program; maps onto autograd.PyLayer."""
    from ..autograd import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            if backward_fn is None:
                raise RuntimeError("static_pylayer: no backward_fn given")
            return backward_fn(*grads)

    return _P.apply(*inputs)


# -- legacy LoD sequence / PS ops: descoped with guidance -------------------

def _lod_descoped(op):
    def fn(*a, **k):
        raise NotImplementedError(
            f"paddle.static.nn.{op}: LoD sequence ops serve the legacy "
            "variable-length pipeline; on TPU use dense padded tensors "
            "(paddle_tpu.nn.functional with masks) — see DESIGN.md ledger")
    fn.__name__ = op
    return fn


sequence_conv = _lod_descoped("sequence_conv")
sequence_softmax = _lod_descoped("sequence_softmax")
sequence_pool = _lod_descoped("sequence_pool")
sequence_first_step = _lod_descoped("sequence_first_step")
sequence_last_step = _lod_descoped("sequence_last_step")
sequence_expand = _lod_descoped("sequence_expand")


def sparse_embedding(*a, **k):
    raise NotImplementedError(
        "paddle.static.nn.sparse_embedding targets parameter-server "
        "training (descoped on TPU, DESIGN.md); use static.nn.embedding or "
        "VocabParallelEmbedding for >HBM vocabularies")


def nce(*a, **k):
    raise NotImplementedError(
        "paddle.static.nn.nce (noise-contrastive estimation over a PS "
        "sampler) is descoped on TPU; use full-softmax cross_entropy — on "
        "TPU the matmul is MXU-bound and vocab-parallel sharding replaces "
        "sampling (DESIGN.md)")
