"""Static-graph API parity layer.

reference: python/paddle/static/. In the TPU-native design there is no
separate static graph runtime — jit.to_static IS the static mode (jaxpr →
XLA). This module provides the API names that matter for porting: InputSpec,
data, Program guards (no-ops), and staged control-flow helpers that map to
lax.cond / lax.while_loop — the contract the reference's static mode offers
via paddle.static.nn.cond/while_loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute
from ..jit import InputSpec  # noqa: F401

__all__ = ["InputSpec", "data", "Program", "program_guard", "default_main_program",
           "default_startup_program", "name_scope", "nn", "cond", "while_loop",
           "scan"]


def data(name, shape, dtype="float32", lod_level=0):
    zeros = jnp.zeros([1 if s in (None, -1) else s for s in shape],
                      dtype=dtype if dtype != "int64" else jnp.int64)
    t = Tensor(zeros)
    t.name = name
    return t


class Program:
    def __init__(self):
        pass

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return Program()


import contextlib


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    yield


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


# -- staged control flow (usable inside jit.to_static traces) ---------------


def cond(pred, true_fn, false_fn, name=None):
    """lax.cond exposed with paddle.static.nn.cond semantics."""
    def f(p):
        return jax.lax.cond(p if p.ndim == 0 else p.reshape(())[()],
                            lambda: _as_arrays(true_fn()),
                            lambda: _as_arrays(false_fn()))
    return execute(f, pred, _name="cond")


def _as_arrays(out):
    return jax.tree_util.tree_map(
        lambda o: o._data if isinstance(o, Tensor) else o, out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    arrs = [v._data if isinstance(v, Tensor) else jnp.asarray(v) for v in loop_vars]

    def f(*a):
        def c(vals):
            r = cond_fn(*[Tensor(v) for v in vals])
            r = r._data if isinstance(r, Tensor) else r
            return r.reshape(())[()] if hasattr(r, "reshape") else r

        def b(vals):
            out = body_fn(*[Tensor(v) for v in vals])
            if not isinstance(out, (list, tuple)):
                out = [out]
            return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                         for o in out)

        return jax.lax.while_loop(c, b, tuple(a))

    out = execute(f, *loop_vars, _name="while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def scan(body_fn, init, xs, name=None):
    def f(carry0, xs_arr):
        def b(c, x):
            nc, y = body_fn(Tensor(c), Tensor(x))
            return (nc._data if isinstance(nc, Tensor) else nc,
                    y._data if isinstance(y, Tensor) else y)
        return jax.lax.scan(b, carry0, xs_arr)
    return execute(f, init, xs, _name="scan")


class nn:
    cond = staticmethod(cond)
    while_loop = staticmethod(while_loop)
