"""Static-graph API parity layer.

reference: python/paddle/static/. In the TPU-native design there is no
separate static graph runtime — jit.to_static IS the static mode (jaxpr →
XLA). This module provides the API names that matter for porting: InputSpec,
data, Program guards (no-ops), and staged control-flow helpers that map to
lax.cond / lax.while_loop — the contract the reference's static mode offers
via paddle.static.nn.cond/while_loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute
from ..jit import InputSpec  # noqa: F401

__all__ = ["InputSpec", "data", "Program", "program_guard", "default_main_program",
           "default_startup_program", "name_scope", "nn", "cond", "while_loop",
           "scan"]


def data(name, shape, dtype="float32", lod_level=0):
    zeros = jnp.zeros([1 if s in (None, -1) else s for s in shape],
                      dtype=dtype if dtype != "int64" else jnp.int64)
    t = Tensor(zeros)
    t.name = name
    return t


class Program:
    """Program shim: the traced jaxpr IS the program, but each Program
    still owns the name-keyed parameter registry its builders write to
    (reference: Program.all_parameters / state_dict). program_guard
    activates a Program's registry for the builders in its scope."""

    def __init__(self):
        self._params: dict = {}
        self._ir = None          # attached pir.Program (last trace)

    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._params = dict(self._params)
        p._ir = self._ir
        return p

    # -- IR surface (reference: Program::Print / Program.__str__) -----------
    def attach_ir(self, pir_program):
        """Bind a captured pir.Program so print(program) shows ops.
        jit.to_static attaches its most recent trace to the default main
        program automatically."""
        self._ir = pir_program

    @property
    def ir(self):
        return self._ir

    def to_string(self, throw_on_error=False, with_details=False):
        """Reference parity: the op-level program text. With an attached
        pir.Program this is the real captured IR (SSA ops, one per
        line); otherwise a parameter-registry summary."""
        if self._ir is not None:
            return self._ir.to_string()
        lines = [f"program (no captured IR; {len(self._params)} "
                 "registered parameters) {"]
        for k, v in self._params.items():
            lines.append(f"  param {k}: {tuple(v.shape)}")
        lines.append("}")
        return "\n".join(lines)

    def __str__(self):
        return self.to_string()

    def all_parameters(self):
        return [p for p in self._params.values() if not p.stop_gradient]

    def state_dict(self, mode="all"):
        """name -> Tensor of registered parameters/buffers. mode: 'param'
        = trainable only, 'opt' = optimizer state (none lives on the
        program here), 'all' = everything (reference: Program.state_dict)."""
        if mode == "param":
            return {k: v for k, v in self._params.items()
                    if not v.stop_gradient}
        if mode == "opt":
            return {}
        if mode != "all":
            raise ValueError(
                f"state_dict mode must be 'param', 'opt' or 'all', got "
                f"{mode!r}")
        return dict(self._params)

    def set_state_dict(self, state_dict):
        """Write values back into the registered tensors IN PLACE so every
        builder closure holding them sees the restored weights
        (reference: Program.set_state_dict). Unknown keys are ignored with
        a warning, matching the reference's lenient load."""
        import warnings
        for k, v in state_dict.items():
            t = self._params.get(k)
            if t is None:
                warnings.warn(f"set_state_dict: skipping unknown "
                              f"parameter {k!r}")
                continue
            # set_value casts dtype AND checks the element count, raising
            # a clear error at load time instead of a far-away shape error
            t.set_value(v)


import contextlib


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """Route static.nn builder parameters into main_program's registry for
    the duration of the block (reference: parameters are appended to the
    guarded Program)."""
    if main_program is None:
        yield
        return
    from . import nn_builders
    prev = nn_builders._param_registry
    nn_builders._param_registry = main_program._params
    try:
        yield
    finally:
        nn_builders._param_registry = prev


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


# -- staged control flow (usable inside jit.to_static traces) ---------------


def cond(pred, true_fn, false_fn, name=None):
    """lax.cond exposed with paddle.static.nn.cond semantics."""
    def f(p):
        return jax.lax.cond(p if p.ndim == 0 else p.reshape(())[()],
                            lambda: _as_arrays(true_fn()),
                            lambda: _as_arrays(false_fn()))
    return execute(f, pred, _name="cond")


def _as_arrays(out):
    return jax.tree_util.tree_map(
        lambda o: o._data if isinstance(o, Tensor) else o, out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    arrs = [v._data if isinstance(v, Tensor) else jnp.asarray(v) for v in loop_vars]

    def f(*a):
        def c(vals):
            r = cond_fn(*[Tensor(v) for v in vals])
            r = r._data if isinstance(r, Tensor) else r
            return r.reshape(())[()] if hasattr(r, "reshape") else r

        def b(vals):
            out = body_fn(*[Tensor(v) for v in vals])
            if not isinstance(out, (list, tuple)):
                out = [out]
            return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                         for o in out)

        return jax.lax.while_loop(c, b, tuple(a))

    out = execute(f, *loop_vars, _name="while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def scan(body_fn, init, xs, name=None):
    def f(carry0, xs_arr):
        def b(c, x):
            nc, y = body_fn(Tensor(c), Tensor(x))
            return (nc._data if isinstance(nc, Tensor) else nc,
                    y._data if isinstance(y, Tensor) else y)
        return jax.lax.scan(b, carry0, xs_arr)
    return execute(f, init, xs, _name="scan")


from . import nn_builders as nn  # noqa: E402  (static-graph layer builders)

# the default main program IS the module-level registry builders write to
# outside any program_guard
_main._params = nn._param_registry
nn.cond = cond
nn.while_loop = while_loop
import sys as _sys  # noqa: E402
_sys.modules[__name__ + ".nn"] = nn  # importable as paddle_tpu.static.nn


# ---------------------------------------------------------------------------
# reference-surface shims (python/paddle/static/__init__.py) — the pieces
# porting code touches; the execution model stays jit.to_static
# ---------------------------------------------------------------------------

Variable = Tensor  # static Variable == Tensor in this architecture


class Executor:
    """reference: base/executor.py Executor — here a thin runner: feed
    tensors in, fetch tensors out; jit owns compilation."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        outs = []
        for f in fetch_list or []:
            if callable(f):
                outs.append(f(**(feed or {})))
            else:
                import numpy as _np
                outs.append(_np.asarray(f._data) if isinstance(f, Tensor)
                            else f)
        return outs

    def close(self):
        pass


class CompiledProgram:
    """reference: compiler.CompiledProgram — XLA compiles under jit; this
    records the program + build strategy for API parity and exposes the
    wrapped program's IR text."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def to_string(self, throw_on_error=False, with_details=False):
        if hasattr(self._program, "to_string"):
            return self._program.to_string()
        return repr(self._program)

    def __str__(self):
        return self.to_string()


class BuildStrategy:
    """reference: BuildStrategy knobs — recorded; XLA owns the passes."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True


class IpuStrategy:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is not a target of this build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is not a target of this build")


import contextlib as _ctx


@_ctx.contextmanager
def device_guard(device=None):
    yield


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU is not a target of this build")
    yield  # pragma: no cover


class _Scope:
    def find_var(self, name):
        return None

    def var(self, name):
        return None


_global_scope = _Scope()


def global_scope():
    return _global_scope


def scope_guard(scope):
    return _ctx.nullcontext(scope)


def cpu_places(device_count=None):
    from ..device import CPUPlace
    import os as _os
    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace()] * n


def cuda_places(device_ids=None):
    """Accelerator places (TPU chips in this build)."""
    import jax as _jax
    from ..device import TPUPlace
    ids = device_ids if device_ids is not None else range(
        len(_jax.devices()))
    return [TPUPlace(i) for i in ids]


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..framework import dtypes as _dt
    t = Tensor(jnp.full(tuple(shape), value, _dt.convert_dtype(dtype)))
    t.persistable = persistable
    t.name = name
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .. import create_parameter as _cp
    return _cp(shape, dtype=dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def Print(input, first_n=-1, message=None, summarize=20, **kw):
    """reference: static Print op — eager host print."""
    import numpy as _np
    prefix = message or "var"
    arr = _np.asarray(input._data)
    if arr.ndim == 0 or summarize < 0:   # reference: -1 = print everything
        shown = arr if arr.ndim == 0 else arr.reshape(-1)
    else:
        shown = arr.reshape(-1)[:summarize]
    print(f"{prefix}: {shown}")
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (reference static.auc). Returns (auc_value, batch_auc,
    state) — state handling collapsed (stateless eager computation)."""
    import numpy as _np
    probs = _np.asarray(input._data)[:, 1] if input._data.ndim == 2 \
        else _np.asarray(input._data)
    y = _np.asarray(label._data).reshape(-1)
    order = _np.argsort(-probs)
    y_sorted = y[order]
    n_pos = max(int(y_sorted.sum()), 0)
    n_neg = len(y_sorted) - n_pos
    if n_pos == 0 or n_neg == 0:
        val = 0.0
    else:
        ranks = _np.empty(len(probs))
        ranks[_np.argsort(probs)] = _np.arange(1, len(probs) + 1)
        val = float((ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2)
                    / (n_pos * n_neg))
    t = Tensor(jnp.asarray(val, jnp.float32))
    return t, t, []


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference: base/backward.py append_backward — in eager-tape terms:
    run backward, return (param, grad) pairs."""
    loss.backward()
    params = parameter_list
    if params is None:
        from ..framework.core import _live_parameters
        params = [p for p in _live_parameters.values()
                  if p is not None and not p.stop_gradient]
    return [(p, p.grad) for p in params if getattr(p, "grad", None)
            is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as _grad
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)
    return outs


class ExponentialMovingAverage:
    """reference: static ExponentialMovingAverage — EMA shadow weights with
    apply/restore guards, eager-tape edition."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []

    def update(self, parameters=None):
        import jax.numpy as _jnp
        params = parameters
        if params is None:
            from ..framework.core import _live_parameters
            params = [p for p in _live_parameters.values() if p is not None]
        for p in params:
            if id(p) not in self._shadow:
                self._shadow[id(p)] = _jnp.array(p._data)
                self._params.append(p)
            else:
                self._shadow[id(p)] = (self._decay * self._shadow[id(p)]
                                       + (1 - self._decay) * p._data)

    @_ctx.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._data
            p._data = self._shadow[id(p)]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))


class WeightNormParamAttr:
    """reference: static WeightNormParamAttr — recorded attr; use
    nn.utils.weight_norm for the actual reparameterization."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name


def _state_to_npz_bytes(state):
    """name->Tensor dict serialized as in-memory npz — no pickle (same
    no-unpickling rule as distributed.checkpoint)."""
    import io as _io
    import numpy as _np
    buf = _io.BytesIO()
    _np.savez(buf, **{k: _np.asarray(v._data) for k, v in state.items()})
    return buf.getvalue()


def _npz_bytes_to_params(data):
    import io as _io
    import numpy as _np
    import jax.numpy as _jnp
    from ..framework.core import Tensor
    out = {}
    if data:
        with _np.load(_io.BytesIO(data)) as z:
            for k in z.files:
                out[k] = Tensor(_jnp.asarray(z[k]))
    return out


def serialize_program(program=None, **kw):
    """The program STRUCTURE is Python + the traced jaxpr (see module
    docstring); the serializable content is the name-keyed parameter
    registry. Format: in-memory npz, no pickle."""
    prog = program or default_main_program()
    return _state_to_npz_bytes(prog.state_dict()
                               if hasattr(prog, "state_dict") else {})


def deserialize_program(data):
    prog = Program()
    prog._params = _npz_bytes_to_params(data)
    return prog


def serialize_persistables(program=None, executor=None, **kw):
    prog = program or default_main_program()
    return _state_to_npz_bytes(prog.state_dict()
                               if hasattr(prog, "state_dict") else {})


def deserialize_persistables(program, data, executor=None):
    state = _npz_bytes_to_params(data)
    if program is not None and hasattr(program, "set_state_dict"):
        program.set_state_dict(state)
    return state


def save(program, model_path, protocol=4, **configs):
    from ..framework.io_file import save as _save
    state = program.state_dict() if hasattr(program, "state_dict") else {}
    _save(state, model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io_file import load as _load
    state = _load(model_path + ".pdparams")
    if hasattr(program, "set_state_dict"):
        program.set_state_dict(state)
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feeds, fetches, **kw):
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """reference: static.save_inference_model. Two flavors:
    - layer=<nn.Layer>: full traced StableHLO artifact via jit.save.
    - static-program (default): the guarded Program's parameter registry
      (.pdmodel, npz bytes) + a feed/fetch manifest (.pdmodel.json); the
      program structure itself is the user's builder code, re-run at load
      (documented Program-shim contract)."""
    import json
    layer = kwargs.get("layer")
    if layer is not None and hasattr(layer, "state_dict") and not isinstance(
            layer, Program):
        from ..jit import save as _jsave
        _jsave(layer, path_prefix)
        return
    prog = kwargs.get("program") or default_main_program()
    save_to_file(path_prefix + ".pdmodel", serialize_program(prog))
    meta = {"format": "paddle_tpu.static", "version": 1,
            "feed": [getattr(v, "name", None) for v in (feed_vars or [])],
            "fetch": [getattr(v, "name", None) for v in (fetch_vars or [])]}
    save_to_file(path_prefix + ".pdmodel.json",
                 json.dumps(meta).encode())


def load_inference_model(path_prefix, executor=None, **kwargs):
    import json
    import os
    if os.path.exists(path_prefix + ".pdmodel.json"):
        prog = deserialize_program(
            load_from_file(path_prefix + ".pdmodel"))
        meta = json.loads(
            load_from_file(path_prefix + ".pdmodel.json").decode())
        target = kwargs.get("program")
        if target is not None and hasattr(target, "set_state_dict"):
            target.set_state_dict(prog.state_dict())
        return [prog, meta.get("feed", []), meta.get("fetch", [])]
    from ..jit import load as _jload
    tl = _jload(path_prefix)
    return [Program(), [], [tl]]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server stack, descoped "
        "on TPU (DESIGN.md)")


def set_program_state(program, state):
    if hasattr(program, "set_state_dict"):
        program.set_state_dict(state)


__all__ += [
    "Variable", "Executor", "CompiledProgram", "BuildStrategy",
    "IpuStrategy", "IpuCompiledProgram", "device_guard", "ipu_shard_guard",
    "global_scope", "scope_guard", "cpu_places", "cuda_places",
    "create_global_var", "create_parameter", "Print", "py_func", "accuracy",
    "auc", "append_backward", "gradients", "ExponentialMovingAverage",
    "WeightNormParamAttr", "serialize_program", "deserialize_program",
    "serialize_persistables", "deserialize_persistables", "save", "load",
    "save_to_file", "load_from_file", "normalize_program",
    "save_inference_model", "load_inference_model", "ctr_metric_bundle",
    "set_program_state",
]


def load_program_state(model_path, var_list=None):
    from ..framework.io_file import load as _load
    return _load(model_path + ".pdparams")


def xpu_places(device_ids=None):
    raise NotImplementedError("XPU is not a target of this build (TPU-native)")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU is not a target of this build")


__all__ += ["load_program_state", "xpu_places", "set_ipu_shard"]
