"""jit.to_static — step compilation on XLA.

reference: python/paddle/jit/ — to_static (api.py:195), SOT bytecode tracer
(sot/translate.py:31), AST transformers, partial_program.

TPU-native design: the reference needs a 35k-LoC bytecode/AST capture stack
because its IR must be built from Python source. Here the imperative API
already runs on jax — so "to_static" is *tracing*: run the function once with
tracers substituted for every live Parameter/buffer, let jax build the jaxpr,
and compile with XLA. Python control flow is hard-staged at trace time (the
documented contract — use paddle_tpu.static.nn.cond/while_loop for
data-dependent control flow, same contract as the reference's static mode).

The compiled callable is itself routed through the autograd tape via one
whole-graph vjp node, so `loss.backward()` after a to_static forward works
exactly like eager — with the entire backward compiled by XLA too.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework import flags as _flags
from ..framework import random as _random
from ..framework.core import Tensor, Parameter, execute

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "TranslatedLayer", "InputSpec"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """reference: python/paddle/static/input.py:InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


def _tensor_leaves(tree):
    return [x for x in jax.tree_util.tree_leaves(
        tree, is_leaf=lambda v: isinstance(v, Tensor)) if isinstance(x, Tensor)]


class StaticFunction:
    """Compiled wrapper. reference analog:
    python/paddle/jit/dy2static/program_translator.py:377 StaticFunction."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 full_graph=False, layer=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._full_graph = full_graph
        self._cache: dict[Any, tuple] = {}   # LRU: insertion == recency
        self._fallback_keys: set = set()
        self._staged_jit_cache: dict = {}   # compiled break segments
        self._last_segments = 0
        self._ir_program = None             # last captured pir.Program
        self._last_report = None            # last pir CompileReport
        functools.wraps(fn)(self)

    @property
    def ir_program(self):
        """The pir.Program of the most recent trace (None when the PIR
        pipeline is disabled or fell back) — `print(sf.ir_program)` is
        the reference's Program.__str__ parity surface."""
        return self._ir_program

    @property
    def last_report(self):
        """pir.CompileReport of the most recent trace: cache hit/miss,
        per-pass edits, pattern counts."""
        return self._last_report

    # -- discovery ----------------------------------------------------------
    def _state_tensors(self):
        if self._layer is not None:
            params = [p for _, p in self._layer.named_parameters()]
            bufs = [b for _, b in self._layer.named_buffers() if b is not None]
        else:
            params = _core.live_parameters()
            bufs = []
        return params, bufs

    def _signature(self, flat_in, params, bufs):
        return (
            tuple((a.shape, str(a.dtype)) for a in flat_in),
            tuple(id(p) for p in params),
            tuple(id(b) for b in bufs),
            tuple((tuple(p._data.shape), str(p._data.dtype)) for p in params),
        )

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs)

        params, bufs = self._state_tensors()
        flat_args, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda v: isinstance(v, Tensor))
        tensor_idx = [i for i, a in enumerate(flat_args) if isinstance(a, Tensor)]
        tensor_in = [flat_args[i] for i in tensor_idx]
        in_arrays = [t._data for t in tensor_in]
        static_rest = [None if i in set(tensor_idx) else a
                       for i, a in enumerate(flat_args)]

        key = (self._signature(in_arrays, params, bufs), treedef,
               tuple((i, repr(a)) for i, a in enumerate(static_rest) if a is not None))
        if key in self._fallback_keys:
            # known graph break: staged mode — ops accumulate in a deferred
            # DAG and each segment between breaks compiles as ONE XLA
            # computation (the SOT partial-graph analog; framework/staging.py)
            return self._run_staged(args, kwargs)
        entry = self._cache.get(key)
        if entry is not None:
            # LRU touch: re-insert so eviction drops the coldest signature
            self._cache.pop(key)
            self._cache[key] = entry
        if entry is None:
            try:
                from ..observability.catalog import metric as _metric
                _metric("jit_retrace_total").inc()
                entry = self._trace(treedef, flat_args, tensor_idx, params,
                                    bufs)
            except jax.errors.ConcretizationTypeError as e:
                # Data-dependent Python control flow reached trace time. The
                # reference's SOT breaks the graph and runs the fragment
                # eagerly (sot/translate.py:31, graph-break fallback);
                # full_graph=True keeps the reference's hard-error contract
                # (use static.nn.cond/while_loop instead).
                if self._full_graph:
                    raise
                import warnings
                warnings.warn(
                    f"to_static: graph break in {getattr(self._fn, '__name__', self._fn)!r} "
                    f"(data-dependent control flow); compiling this input "
                    f"signature as staged prefix segments around the break. "
                    f"Use paddle_tpu.static.nn.cond/while_loop or "
                    f"full_graph=True to make this an error.\n"
                    f"  cause: {e}", RuntimeWarning, stacklevel=2)
                self._fallback_keys.add(key)
                return self._run_staged(args, kwargs)
            self._cache[key] = entry
            # size-capped signature cache: unbounded retrace/recompile on
            # shape churn was silent; now the coldest signature is evicted
            # and every fresh trace shows in jit_retrace_total
            cap = _flags.flag_value("jit_signature_cache_size")
            while cap and len(self._cache) > cap:
                self._cache.pop(next(iter(self._cache)))
        jitted, out_rebuild, mutated = entry

        p_arrays = [p._data for p in params]
        b_arrays = [b._data for b in bufs]
        rng_key = _random.next_key()

        n_tr = sum(1 for p in params if not p.stop_gradient)
        trainable = [p for p in params if not p.stop_gradient]
        frozen = [p._data for p in params if p.stop_gradient]

        def run(*diff_and_inputs):
            tr = diff_and_inputs[:n_tr]
            inp = diff_and_inputs[n_tr:]
            return jitted(list(tr), frozen, b_arrays, rng_key, *inp)

        outs = execute(run, *(trainable + tensor_in), _name="to_static")
        flat_outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        n_user = len(flat_outs) - len(mutated)
        user_out = flat_outs[:n_user]
        for t, new in zip(mutated, flat_outs[n_user:]):
            t._data = new._data
            # buffer updates are state, not autograd outputs
            new._node = None
        return out_rebuild(user_out)

    def _run_staged(self, args, kwargs):
        """Run the function in staged mode (graph-break path)."""
        scope = _core._staging.StagingScope(jit_cache=self._staged_jit_cache)
        with scope:
            out = self._fn(*args, **kwargs)
        self._last_segments = scope.segments
        return out

    def _trace(self, treedef, flat_args, tensor_idx, params, bufs):
        """Build + jit the pure function. Runs the python body exactly once
        per (shape, dtype) signature — the analog of program capture in the
        reference's ProgramTranslator."""
        fn = self._fn
        tensor_set = set(tensor_idx)
        trainable = [p for p in params if not p.stop_gradient]
        frozen_params = [p for p in params if p.stop_gradient]
        out_struct = {}

        def pure(tr_arrays, frozen_arrays, buf_arrays, rng_key, *input_arrays):
            saved = [(t, t._data, t._node, t.stop_gradient)
                     for t in trainable + frozen_params + bufs]
            ctx = _core.TraceContext()
            try:
                for t, a in zip(trainable, tr_arrays):
                    t._data = a
                    t._node = None
                for t, a in zip(frozen_params, frozen_arrays):
                    t._data = a
                    t._node = None
                for t, a in zip(bufs, buf_arrays):
                    t._data = a
                    t._node = None
                it = iter(input_arrays)
                rebuilt = [
                    Tensor(next(it), stop_gradient=flat_args[i].stop_gradient)
                    if i in tensor_set else a
                    for i, a in enumerate(flat_args)]
                args2, kwargs2 = jax.tree_util.tree_unflatten(treedef, rebuilt)
                with ctx, _random._global_rng.trace_scope(rng_key):
                    out = fn(*args2, **kwargs2)
                out_flat, out_tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda v: isinstance(v, Tensor))
                out_arrays = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                              for o in out_flat]
                mutated = [t for t in ctx.mutations.values()]
                mut_arrays = [t._data for t in mutated]
                out_struct["tree"] = out_tree
                out_struct["mutated"] = mutated
                out_struct["n"] = len(out_arrays)
                return tuple(out_arrays) + tuple(mut_arrays)
            finally:
                for t, a, node, sg in saved:
                    t._data = a
                    t._node = node
                    t.stop_gradient = sg

        p_arrays = [p._data for p in trainable]
        f_arrays = [p._data for p in frozen_params]
        b_arrays = [b._data for b in bufs]
        in_arrays = [flat_args[i]._data for i in tensor_idx]

        jitted = None
        if _flags.flag_value("pir"):
            # PIR pipeline: capture -> passes (DCE/fold/CSE/DRR patterns)
            # -> persistent compile cache consulted pre-XLA. The capture
            # trace populates out_struct; any pipeline failure degrades
            # back to the plain jax.jit path below.
            jitted = self._trace_pir(pure, p_arrays, f_arrays, b_arrays,
                                     in_arrays)
        if jitted is None:
            jitted = jax.jit(pure, static_argnums=())
            # force trace now to learn output structure
            _ = jax.eval_shape(pure, p_arrays, f_arrays, b_arrays,
                               jax.random.key(0), *in_arrays)

        out_tree = out_struct["tree"]
        mutated = out_struct["mutated"]

        def rebuild(user_out_tensors):
            return jax.tree_util.tree_unflatten(out_tree, user_out_tensors)

        return jitted, rebuild, mutated

    def _trace_pir(self, pure, p_arrays, f_arrays, b_arrays, in_arrays):
        """Compile `pure` through paddle_tpu.pir (pipeline + persistent
        cache). Returns a callable with the plain-jit calling convention
        or None to use the plain path. ConcretizationTypeError
        propagates untouched — the graph-break contract stays with
        __call__."""
        import jax.random as jrandom
        n_tr, n_fr, n_b = len(p_arrays), len(f_arrays), len(b_arrays)
        k_idx = n_tr + n_fr + n_b
        kd0 = jrandom.key_data(jrandom.key(0))

        def flat_fn(*flat):
            return pure(list(flat[:n_tr]), list(flat[n_tr:n_tr + n_fr]),
                        list(flat[n_tr + n_fr:k_idx]),
                        jrandom.wrap_key_data(flat[k_idx]),
                        *flat[k_idx + 1:])

        try:
            from .. import pir as _pir
            compiled, report = _pir.compile_flat(
                flat_fn, [*p_arrays, *f_arrays, *b_arrays, kd0, *in_arrays],
                name=getattr(self._fn, "__name__", "to_static"))
        except jax.errors.ConcretizationTypeError:
            raise
        except Exception as e:  # noqa: BLE001 — degrade to plain jax.jit
            import warnings
            warnings.warn(f"to_static: PIR pipeline unavailable "
                          f"({e!r}); compiling with plain jax.jit",
                          RuntimeWarning, stacklevel=3)
            return None
        self._last_report = report
        self._ir_program = report.program
        if report.program is not None:
            try:
                # Paddle parity: print(static.default_main_program())
                # shows the ops of the most recent trace
                from .. import static as _static
                _static.default_main_program().attach_ir(report.program)
            except Exception:  # noqa: BLE001 — parity surface is optional
                pass

        def jitted(tr, frozen, bufs2, rng_key, *inputs):
            return compiled(*tr, *frozen, *bufs2,
                            jrandom.key_data(rng_key), *inputs)

        return jitted

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """reference: python/paddle/jit/api.py:195. Default full_graph=False
    matches the reference's SOT mode: trace failures from data-dependent
    Python control flow fall back to eager for that input signature (graph
    break) instead of raising; full_graph=True restores the hard error."""

    def decorate(fn):
        from ..nn import Layer
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec, build_strategy,
                                full_graph, layer=layer)
            layer.forward = sf
            return layer
        layer = getattr(fn, "__self__", None)
        from ..nn import Layer as _L
        layer = layer if isinstance(layer, _L) else None
        return StaticFunction(fn, input_spec, build_strategy, full_graph,
                              layer=layer)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# jit.save / jit.load (reference: python/paddle/jit/api.py save/load)
# ---------------------------------------------------------------------------


class TranslatedLayer:
    """Loaded inference artifact."""

    def __init__(self, fn, state):
        self._fn = fn
        self._state = state

    def __call__(self, *args):
        return self._fn(*args)


def save(layer, path, input_spec=None, **configs):
    """Serialize an inference program: params (.pdiparams) + the traced,
    XLA-portable StableHLO program (.pdmodel via jax.export).

    reference: python/paddle/jit/api.py save — where the reference serializes
    a PIR program (paddle/fluid/pir/serialize_deserialize/), the TPU-native
    artifact is StableHLO, XLA's stable exchange format: it reloads on any
    future jax/XLA and runs on TPU without the model class.
    """
    import os
    import pickle
    import numpy as np
    from ..framework import dtypes as _dt

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    if hasattr(layer, "state_dict"):
        for k, v in layer.state_dict().items():
            state[k] = np.asarray(v._data)
    # params as npz (no pickle on the load path), atomic rename. Non-builtin
    # dtypes (bfloat16/fp8 from ml_dtypes have numpy kind 'V') would be
    # silently written as raw void by savez — encode them as uint8 bytes and
    # record the real dtype in the metadata.
    npz_state, param_dtypes = {}, {}
    for k, v in state.items():
        npz_state[k], param_dtypes[k] = _encode_param(v)
    tmp = path + ".pdiparams.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **npz_state)
    os.replace(tmp, path + ".pdiparams")

    meta = {"format_version": FORMAT_VERSION,
            "class": type(layer).__name__,
            "param_dtypes": param_dtypes,
            "input_spec": [(tuple(s.shape), str(s.dtype))
                           for s in (input_spec or [])],
            "stablehlo": None}
    if input_spec:
        from ..parallel.functional import functional_call
        was_training = getattr(layer, "training", False)
        if hasattr(layer, "eval"):
            layer.eval()

        def fwd(params, *inputs):
            return functional_call(layer, params, *inputs)

        try:
            arg_specs = [jax.ShapeDtypeStruct(tuple(s.shape),
                                              _dt.convert_dtype(s.dtype))
                         for s in input_spec]
            params_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                           for k, v in state.items()}
            exported = jax.export.export(jax.jit(fwd))(params_spec, *arg_specs)
            meta["stablehlo"] = exported.serialize()
        finally:
            if was_training and hasattr(layer, "train"):
                layer.train()
    tmp = path + ".pdmodel.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(meta, f)
    os.replace(tmp, path + ".pdmodel")


FORMAT_VERSION = 2  # v1: pickled params dict; v2: npz params + version field


def _encode_param(v):
    """(npz-safe array, dtype descriptor). Builtin dtypes pass through;
    kind-'V' ml_dtypes (bfloat16, float8_*) become uint8 bytes."""
    import numpy as np
    if v.dtype.kind == "V":
        raw = np.frombuffer(v.tobytes(), np.uint8).reshape(
            v.shape + (v.dtype.itemsize,))
        return raw, {"dtype": str(v.dtype), "encoded": True}
    return v, {"dtype": str(v.dtype), "encoded": False}


def _decode_param(arr, desc):
    import numpy as np
    if not desc or not desc.get("encoded"):
        return arr
    import ml_dtypes  # registers bfloat16/fp8 with numpy
    dt = np.dtype(desc["dtype"])
    return np.frombuffer(arr.tobytes(), dt).reshape(arr.shape[:-1])


def _load_npz_params(path, meta):
    import numpy as np
    dtypes = meta.get("param_dtypes", {})
    with np.load(path, allow_pickle=False) as z:
        return {k: _decode_param(z[k], dtypes.get(k)) for k in z.files}


def load(path, **configs):
    """Load a jit.save artifact as a callable TranslatedLayer (runs the
    serialized StableHLO program when present). Rejects artifacts from a
    newer format with a clear message (reference keeps version patches in
    pir/serialize_deserialize/patch_util.h; our format is versioned the
    same way)."""
    import pickle
    import numpy as np
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    version = meta.get("format_version", 1)
    if version > FORMAT_VERSION:
        raise ValueError(
            f"jit artifact {path!r} has format version {version}; this "
            f"build reads <= {FORMAT_VERSION}. Load it with a newer "
            "paddle_tpu or re-save with this one.")
    if version >= 2:
        state = _load_npz_params(path + ".pdiparams", meta)
    else:  # v1 pickled dict
        with open(path + ".pdiparams", "rb") as f:
            state = pickle.load(f)
    if meta.get("stablehlo"):
        exported = jax.export.deserialize(meta["stablehlo"])

        def fn(*args):
            arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
            out = exported.call(state, *arrs)
            return jax.tree_util.tree_map(Tensor, out)
    else:
        def fn(*args):
            raise RuntimeError(
                "this artifact was saved without input_spec (params only); "
                "re-instantiate the model class and call set_state_dict")
    tl = TranslatedLayer(fn, state)
    tl.state_dict = lambda: state
    tl._input_spec = meta.get("input_spec", [])
    return tl


_code_level = 0
_verbosity = 0


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit/dy2static logging_utils.set_code_level — controls
    transformed-code logging. Recorded; trace-based to_static has no AST
    transforms to print, so this gates the trace-debug logs."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit logging_utils.set_verbosity."""
    global _verbosity
    _verbosity = level
