"""Optimizers with a functional core.

reference: python/paddle/optimizer/ (optimizer.py base + 17 optimizers).

Design: every optimizer defines pure functions
    init_state(param_array) -> dict[str, array]
    update(param, grad, state, lr, step, **hyper) -> (new_param, new_state)
The imperative `.step()` applies them per-parameter eagerly (rebinding
Tensor._data); `jit.to_static`/hapi compile the same functions over whole
parameter pytrees — one fused XLA update kernel, the analog of the
reference's fused multi-tensor optimizer kernels
(paddle/phi/kernels/gpu/fused_adam_kernel.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Parameter, Tensor, no_grad
from . import lr as lr_mod
from .lr import *  # noqa: F401,F403
from .lr import LRScheduler

__all__ = [
    "ASGD","Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "Adamax", "RMSProp", "Lamb", "NAdam", "RAdam",
           "Rprop", "LBFGS", "lr"]

lr = lr_mod


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, float):
            self._weight_decay = weight_decay
        else:  # L2Decay object
            self._weight_decay = getattr(weight_decay, "_coeff", float(weight_decay))
        self._accumulators: dict[int, dict] = {}
        self._step_count = 0

    # -- functional core (override) ----------------------------------------
    def init_state(self, p):
        return {}

    def update(self, p, g, state, lr, step):
        raise NotImplementedError

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return self._learning_rate

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- stepping ------------------------------------------------------------
    @no_grad()
    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr_v = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            st = self._accumulators.get(id(p))
            if st is None:
                st = self.init_state(p._data)
                self._accumulators[id(p)] = st
            g_arr = g._data if isinstance(g, Tensor) else g
            if g_arr.dtype != p._data.dtype:
                g_arr = g_arr.astype(p._data.dtype)
            p_lr = lr_v * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            new_p, new_st = self.update(p._data, g_arr, st, p_lr, self._step_count)
            p._data = new_p.astype(p._data.dtype)
            self._accumulators[id(p)] = new_st

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- state dict ----------------------------------------------------------
    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._parameter_list):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    sd[f"{p.name or i}_{k}"] = Tensor(v)
        sd["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step", 0))
        for i, p in enumerate(self._parameter_list):
            st = self.init_state(p._data)
            found = False
            for k in st:
                key = f"{p.name or i}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                    found = True
            if found:
                self._accumulators[id(p)] = st
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    # -- tree-level functional API (used by jit/hapi fast path) -------------
    def tree_init(self, params_tree):
        return jax.tree_util.tree_map(self.init_state, params_tree)

    def tree_update(self, params_tree, grads_tree, states_tree, lr_v, step):
        is_state = lambda x: isinstance(x, dict) and not any(
            isinstance(v, dict) for v in x.values())
        flat_p, treedef = jax.tree_util.tree_flatten(params_tree)
        flat_g = treedef.flatten_up_to(grads_tree)
        flat_s = jax.tree_util.tree_flatten(states_tree, is_leaf=is_state)[0]
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            np_, ns_ = self.update(p, g.astype(p.dtype), s, lr_v, step)
            # keep param/state dtypes stable: update math may promote to f32
            # (e.g. beta**step with a traced step); cast back so bf16 training
            # stays bf16 and jit signatures never change across steps
            new_p.append(np_.astype(p.dtype))
            new_s.append({k: v.astype(s[k].dtype) if hasattr(v, "astype") else v
                          for k, v in ns_.items()})
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        return p - lr * g, state


class ASGD(Optimizer):
    """Averaged SGD (Polyak-Ruppert). reference: optimizer/asgd.py — keeps
    a running average of the iterates alongside the SGD step."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._batch_num = batch_num

    def init_state(self, p):
        # d = running sum over the window; ys = the last `batch_num` grads
        return {"d": jnp.zeros_like(p),
                "ys": jnp.zeros((self._batch_num,) + p.shape, p.dtype)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        # reference asgd.py: evict the oldest grad from the window sum,
        # admit the new one; param -= lr * d / n
        idx = jnp.mod(jnp.asarray(step - 1, jnp.int32), self._batch_num)
        oldest = jax.lax.dynamic_index_in_dim(state["ys"], idx, 0,
                                              keepdims=False)
        d = state["d"] - oldest + g
        ys = jax.lax.dynamic_update_index_in_dim(
            state["ys"], g.astype(state["ys"].dtype), idx, 0)
        n = jnp.minimum(jnp.asarray(step, jnp.float32),
                        jnp.float32(self._batch_num))
        p_new = p - lr * d / jnp.maximum(n, 1.0)
        return p_new, {"d": d, "ys": ys}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad
        self._decoupled_wd = False

    def init_state(self, p):
        st = {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros_like(p)
        return st

    def update(self, p, g, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        if self._weight_decay and not self._decoupled_wd:
            g = g + self._weight_decay * p
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * (g * g)
        mhat = m / (1 - b1 ** step)
        if self._amsgrad:
            vmax = jnp.maximum(state.get("moment2_max", v), v)
            vhat = vmax / (1 - b2 ** step)
        else:
            vhat = v / (1 - b2 ** step)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        if self._weight_decay and self._decoupled_wd:
            upd = upd + lr * self._weight_decay * p
        new_state = {"moment1": m, "moment2": v}
        if self._amsgrad:
            new_state["moment2_max"] = vmax
        return p - upd, new_state


class AdamW(Adam):
    """Decoupled weight decay. reference: python/paddle/optimizer/adamw.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, name=name, amsgrad=amsgrad)
        self._decoupled_wd = True
        self._apply_decay_fn = apply_decay_param_fun


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        acc = state["moment"] + g * g
        return p - lr * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._rho = rho

    def init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        rho, eps = self._rho, self._eps
        asg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        return p - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        p_new = p - lr / (1 - b1 ** step) * m / (u + self._eps)
        return p_new, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum = momentum
        self._centered = centered

    def init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p), "velocity": jnp.zeros_like(p)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p)
        return st

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        rho = self._rho
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        v = self._momentum * state["velocity"] + lr * g / denom
        st = {"mean_square": ms, "velocity": v}
        if mg is not None:
            st["mean_grad"] = mg
        return p - v, st


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._weight_decay * p
        w_norm = jnp.linalg.norm(p.reshape(-1).astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.reshape(-1).astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0).astype(p.dtype)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p),
                "mu_product": jnp.ones((), jnp.float32)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        mu_t = b1 * (1 - 0.5 * 0.96 ** (step * self._psi))
        mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((step + 1) * self._psi))
        mu_prod = state["mu_product"] * mu_t
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * g / (1 - mu_prod)
        vhat = v / (1 - b2 ** step)
        return (p - lr * mhat / (jnp.sqrt(vhat) + self._eps),
                {"moment1": m, "moment2": v, "mu_product": mu_prod})


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step)
        rho_inf = 2 / (1 - b2) - 1
        rho_t = rho_inf - 2 * step * b2 ** step / (1 - b2 ** step)
        if rho_t > 5:
            l_t = jnp.sqrt((1 - b2 ** step)) / (jnp.sqrt(v) + self._eps)
            r_t = ((rho_t - 4) * (rho_t - 2) * rho_inf /
                   ((rho_inf - 4) * (rho_inf - 2) * rho_t)) ** 0.5
            upd = lr * mhat * r_t * l_t
        else:
            upd = lr * mhat
        return p - upd, {"moment1": m, "moment2": v}


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_range = learning_rate_range
        self._etas = etas

    def init_state(self, p):
        return {"prev_grad": jnp.zeros_like(p),
                "lr": jnp.full_like(p, self.get_lr())}

    def update(self, p, g, state, lr, step):
        sign = jnp.sign(g * state["prev_grad"])
        eta = jnp.where(sign > 0, self._etas[1],
                        jnp.where(sign < 0, self._etas[0], 1.0))
        new_lr = jnp.clip(state["lr"] * eta, self._lr_range[0], self._lr_range[1])
        g_eff = jnp.where(sign < 0, 0.0, g)
        return (p - new_lr * jnp.sign(g_eff),
                {"prev_grad": g_eff, "lr": new_lr})


class LBFGS(Optimizer):
    """reference: python/paddle/optimizer/lbfgs.py — full-batch quasi-Newton."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self._history = []

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS requires a closure")
        loss = closure()
        flat = lambda: jnp.concatenate([p.grad._data.reshape(-1).astype(jnp.float32)
                                        for p in self._parameter_list])
        flat_p = lambda: jnp.concatenate([p._data.reshape(-1).astype(jnp.float32)
                                          for p in self._parameter_list])
        g = flat()
        x = flat_p()
        # two-loop recursion
        q = g
        alphas = []
        for s, y, rho in reversed(self._history):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._history:
            s, y, _ = self._history[-1]
            gamma = jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-10)
            q = gamma * q
        for (s, y, rho), a in zip(self._history, reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        d = -q
        lr_v = self.get_lr()
        x_new = x + lr_v * d
        # write back
        offset = 0
        for p in self._parameter_list:
            n = p._data.size
            p._data = x_new[offset:offset + n].reshape(p._data.shape).astype(p._data.dtype)
            offset += n
        # curvature update needs next grad; recompute closure
        for p in self._parameter_list:
            p.clear_grad()
        loss2 = closure()
        g_new = flat()
        s_vec = x_new - x
        y_vec = g_new - g
        sy = jnp.dot(s_vec, y_vec)
        if float(sy) > 1e-10:
            self._history.append((s_vec, y_vec, 1.0 / sy))
            if len(self._history) > self.history_size:
                self._history.pop(0)
        return loss
