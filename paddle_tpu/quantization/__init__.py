"""Quantization (QAT + PTQ). reference: python/paddle/quantization/
(config.py QuantConfig, qat.py QAT, ptq.py PTQ, observers/, quanters/).

TPU-native: "int8 kernels" are simulated-quant (quant-dequant) graphs — XLA
fuses the scale/round/clip chain into the surrounding matmul, and the
straight-through estimator makes QAT differentiable. Observers collect
ranges in eager mode; convert() freezes scales into the layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, execute
from ..nn.layer.layers import Layer
from .. import nn

__all__ = ["QuantConfig", "QAT", "PTQ", "BaseQuanter", "BaseObserver",
           "AbsmaxObserver", "EMAObserver", "FakeQuanterWithAbsMaxObserver",
           "quanted_layers"]


def _fake_quant(x, scale, bits=8):
    """Quant-dequant with straight-through gradient. A zero scale means the
    observer has seen no data yet — pass the value through unquantized
    instead of collapsing everything into the [-1e-8, 1e-8] bucket."""
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    q = jnp.where(scale > 0, q, x)
    return x + jax.lax.stop_gradient(q - x)


# ---------------------------------------------------------------------------
# observers / quanters
# ---------------------------------------------------------------------------

class BaseObserver(Layer):
    """reference: python/paddle/quantization/factory.py ObserverFactory."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self.register_buffer("_scale", Tensor(jnp.zeros((), jnp.float32)))

    def scales(self):
        return self._scale

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def _observe(self, x):
        raise NotImplementedError

    def forward(self, x):
        self._observe(x)
        return x


class AbsmaxObserver(BaseObserver):
    """Running max of |x|. reference: quantization/observers/abs_max.py."""

    def _observe(self, x):
        from ..framework.core import buffer_update
        cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
        buffer_update(self._scale, jnp.maximum(self._scale._data, cur))


class EMAObserver(BaseObserver):
    """EMA of batch absmax. reference: observers/emd? (mse/ema family)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self._rate = moving_rate

    def _observe(self, x):
        from ..framework.core import buffer_update
        cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
        prev = self._scale._data
        new = jnp.where(prev == 0, cur, self._rate * prev + (1 - self._rate) * cur)
        buffer_update(self._scale, new)


class BaseQuanter(Layer):
    pass


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT quanter: observe absmax (EMA) + fake-quant with STE.
    reference: quantization/quanters/abs_max.py
    FakeQuanterWithAbsMaxObserverLayer."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32"):
        super().__init__()
        self._observer = EMAObserver(quant_bits, moving_rate)
        self._quant_bits = quant_bits

    def scales(self):
        return self._observer.scales()

    def bit_length(self):
        return self._quant_bits

    def forward(self, x):
        if self.training:
            self._observer._observe(x)
        scale = self._observer._scale._data
        return execute(lambda a: _fake_quant(a, scale, self._quant_bits), x,
                       _name="fake_quant")


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class _SingleLayerConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """reference: python/paddle/quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None):
        self._global = _SingleLayerConfig(activation, weight)
        self._layer_configs = []   # (predicate, config)
        self._type_configs = []    # (layer_type, config)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs.append(
                (l, _SingleLayerConfig(activation, weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs.append((t, _SingleLayerConfig(activation, weight)))

    def _config_for(self, layer):
        for l, cfg in self._layer_configs:
            if layer is l:
                return cfg
        for t, cfg in self._type_configs:
            if isinstance(layer, t):
                return cfg
        if self._global.activation or self._global.weight:
            return self._global
        return None


def _make(factory):
    return factory() if callable(factory) else factory


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------

class _QuantedBase(Layer):
    """Quanter attrs are set only when present — assigning None into
    __dict__ would shadow later sublayer registration in Layer.__setattr__."""

    def __init__(self, inner, cfg):
        super().__init__()
        self._inner = inner
        if cfg.weight:
            self.weight_quanter = _make(cfg.weight)
        if cfg.activation:
            self.activation_quanter = _make(cfg.activation)

    @property
    def _wq(self):
        return getattr(self, "weight_quanter", None)

    @property
    def _aq(self):
        return getattr(self, "activation_quanter", None)


class QuantedLinear(_QuantedBase):
    """reference: python/paddle/nn/quant/qat/linear.py QuantedLinear."""

    def forward(self, x):
        w = self._inner.weight
        if self._wq is not None:
            w = self._wq(w)
        if self._aq is not None:
            x = self._aq(x)
        from ..nn import functional as F
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(_QuantedBase):
    def forward(self, x):
        inner = self._inner
        w = inner.weight
        if self._wq is not None:
            w = self._wq(w)
        if self._aq is not None:
            x = self._aq(x)
        from ..nn import functional as F
        return F.conv2d(x, w, inner.bias, inner._stride, inner._padding,
                        inner._dilation, inner._groups, inner._data_format)


quanted_layers = {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}


def _wrap_model(model, config, wrap, original=None):
    """Walk `model` (possibly a deepcopy) in lockstep with `original` so
    identity-based add_layer_config entries still resolve after copying."""
    original = original if original is not None else model
    for name, sub in list(model._sub_layers.items()):
        orig_sub = original._sub_layers.get(name, sub)
        cfg = config._config_for(orig_sub)
        cls = quanted_layers.get(type(sub))
        if cfg is not None and cls is not None:
            model._sub_layers[name] = wrap(cls, sub, cfg)
        else:
            _wrap_model(sub, config, wrap, orig_sub)
    return model


# ---------------------------------------------------------------------------
# QAT / PTQ drivers
# ---------------------------------------------------------------------------

class QAT:
    """reference: python/paddle/quantization/qat.py QAT."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=False):
        original = model
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return _wrap_model(model, self._config,
                           lambda cls, sub, cfg: cls(sub, cfg),
                           original=original)

    def convert(self, model, inplace=False):
        """Freeze observers (stop updating scales) for export."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        model.eval()
        return model


class PTQ:
    """reference: python/paddle/quantization/ptq.py PTQ — insert observers,
    calibrate with sample data, then convert() freezes scales into
    fake-quant layers."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=False):
        original = model
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        class _Observed(Layer):
            def __init__(self, inner, cfg):
                super().__init__()
                self._inner = inner
                self.act_observer = _make(cfg.activation) if cfg.activation else None
                self.w_observer = _make(cfg.weight) if cfg.weight else None
                if self.w_observer is not None:
                    self.w_observer(inner.weight)  # weights are static

            def forward(self, x):
                if self.act_observer is not None:
                    x = self.act_observer(x)
                return self._inner(x)

        return _wrap_model(model, self._config,
                           lambda cls, sub, cfg: _Observed(sub, cfg),
                           original=original)

    def convert(self, model, inplace=False):
        """Replace observed layers with fake-quant layers using the
        calibrated scales."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def walk(m):
            for name, sub in list(m._sub_layers.items()):
                if type(sub).__name__ == "_Observed":
                    inner = sub._inner
                    cls = quanted_layers.get(type(inner))
                    cfg = _SingleLayerConfig(None, None)
                    q = cls(inner, cfg)
                    if sub.w_observer is not None:
                        fq = FakeQuanterWithAbsMaxObserver(
                            quant_bits=sub.w_observer.bit_length())
                        from ..framework.core import buffer_update
                        buffer_update(fq._observer._scale,
                                      sub.w_observer._scale._data)
                        fq.eval()
                        q.weight_quanter = fq
                    if sub.act_observer is not None:
                        fq = FakeQuanterWithAbsMaxObserver(
                            quant_bits=sub.act_observer.bit_length())
                        from ..framework.core import buffer_update
                        buffer_update(fq._observer._scale,
                                      sub.act_observer._scale._data)
                        fq.eval()
                        q.activation_quanter = fq
                    m._sub_layers[name] = q
                else:
                    walk(sub)

        walk(model)
        model.eval()
        return model


def quanter(name):
    """reference: quantization/factory.py quanter — class decorator that
    registers a quanter under `name` and synthesizes a factory."""
    def deco(cls):
        existing = globals().get(name)
        if existing is not None and existing is not cls:
            raise ValueError(
                f"quanter name {name!r} collides with an existing "
                "paddle_tpu.quantization export; pick another name")
        globals()[name] = cls
        _QUANTER_REGISTRY[name] = cls
        return cls
    return deco


_QUANTER_REGISTRY = {}
