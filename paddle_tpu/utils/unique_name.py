"""Unique name generator. reference: python/paddle/utils/unique_name.py
(re-export of base/unique_name.py: generate, guard, switch)."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key):
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key):
    return _generator(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        global _generator
        _generator = old
