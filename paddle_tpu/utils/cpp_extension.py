"""Custom C++ operators. reference: python/paddle/utils/cpp_extension/
(extension_utils.py, cpp_extension.py load:...) + the C++ registration path
paddle/fluid/framework/custom_operator.cc.

TPU-native design: a custom C++ op cannot run ON the TPU (device code is
XLA-compiled), so — exactly like the reference's custom CPU ops — the C++
function runs on the host, bridged into jit-compiled programs with
jax.pure_callback. The build is g++ -shared (no pybind11; the C ABI below
is the binding layer), cached by source hash.

C ABI contract for an op named NAME:
    void NAME(const void** inputs, void** outputs,
              const int64_t* const* in_shapes, const int* in_ndims,
              int num_inputs);
Inputs/outputs are contiguous arrays; output buffers are pre-allocated by
the caller from the declared output spec.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["load", "CustomOpLibrary", "CppExtension", "CUDAExtension",
           "setup", "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
             verbose=False):
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    # flags are part of the cache key — a flag change must rebuild
    h.update(repr((sorted(extra_cxx_cflags or []),
                   sorted(extra_ldflags or []))).encode())
    tag = h.hexdigest()[:16]
    so_path = os.path.join(get_build_directory(), f"{name}_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17"]
    cmd += list(extra_cxx_cflags or [])
    cmd += ["-o", so_path] + list(sources) + list(extra_ldflags or [])
    if verbose:
        print("cpp_extension:", " ".join(cmd))
    res = subprocess.run(cmd, capture_output=not verbose)
    if res.returncode != 0:
        diag = (res.stderr or b"").decode(errors="replace") \
            if not verbose else "(see output above)"
        raise RuntimeError(
            f"cpp_extension build of {name} failed "
            f"(command: {' '.join(cmd)}):\n{diag}")
    return so_path


class CustomOpLibrary:
    """A loaded custom-op shared library; ops become jit-compatible python
    callables via jax.pure_callback."""

    def __init__(self, so_path):
        self._path = so_path
        self._lib = ctypes.CDLL(so_path)

    def _raw(self, symbol):
        fn = getattr(self._lib, symbol)
        fn.restype = None
        fn.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                       ctypes.POINTER(ctypes.c_void_p),
                       ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                       ctypes.POINTER(ctypes.c_int),
                       ctypes.c_int]
        return fn

    def op(self, symbol, out_shapes_fn=None, out_dtypes_fn=None):
        """Build a callable. out_shapes_fn(*input_shapes) -> list of output
        shapes (default: same as first input); out_dtypes_fn likewise."""
        import jax
        from ..framework.core import Tensor, execute

        fn = self._raw(symbol)

        def host_call(*arrays):
            arrays = [np.ascontiguousarray(a) for a in arrays]
            in_shapes = [a.shape for a in arrays]
            o_shapes = (out_shapes_fn(*in_shapes) if out_shapes_fn
                        else [in_shapes[0]])
            o_dtypes = (out_dtypes_fn(*[a.dtype for a in arrays])
                        if out_dtypes_fn else [arrays[0].dtype] * len(o_shapes))
            outs = [np.empty(s, d) for s, d in zip(o_shapes, o_dtypes)]
            n = len(arrays)
            in_ptrs = (ctypes.c_void_p * n)(
                *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
            out_ptrs = (ctypes.c_void_p * len(outs))(
                *[o.ctypes.data_as(ctypes.c_void_p) for o in outs])
            shape_arrs = [np.asarray(a.shape, np.int64) for a in arrays]
            shape_ptrs = (ctypes.POINTER(ctypes.c_int64) * n)(
                *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
                  for s in shape_arrs])
            ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
            fn(in_ptrs, out_ptrs, shape_ptrs, ndims, n)
            return outs if len(outs) > 1 else outs[0]

        def jax_fn(*arrays):
            in_shapes = [a.shape for a in arrays]
            o_shapes = (out_shapes_fn(*in_shapes) if out_shapes_fn
                        else [in_shapes[0]])
            o_dtypes = (out_dtypes_fn(*[a.dtype for a in arrays])
                        if out_dtypes_fn else [arrays[0].dtype] * len(o_shapes))
            specs = [jax.ShapeDtypeStruct(s, d)
                     for s, d in zip(o_shapes, o_dtypes)]
            out = jax.pure_callback(
                host_call, specs if len(specs) > 1 else specs[0], *arrays)
            return out

        def tensor_fn(*tensors):
            return execute(jax_fn, *tensors, _name=symbol)

        tensor_fn.__name__ = symbol
        tensor_fn.raw = jax_fn
        return tensor_fn


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False):
    """Compile + load a custom-op library.
    reference: python/paddle/utils/cpp_extension/cpp_extension.py load."""
    cflags = list(extra_cxx_cflags or [])
    for inc in extra_include_paths or []:
        cflags.append(f"-I{inc}")
    so = _compile(name, sources, cflags, extra_ldflags, verbose)
    return CustomOpLibrary(so)


class CppExtension:
    """setup()-style declaration (reference API parity)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(sources, *args, **kwargs):
    """Accepted for source compatibility; on TPU there is no CUDA — the op
    builds as a host C++ extension."""
    return CppExtension(sources, *args, **kwargs)


def setup(name=None, ext_modules=None, **kwargs):
    """Eager build of declared extensions (the reference's setuptools path
    collapses to a direct g++ build here)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else [ext_modules]
    libs = [load(name or f"ext{i}", e.sources, **e.kwargs)
            for i, e in enumerate(exts) if e is not None]
    return libs[0] if len(libs) == 1 else libs
