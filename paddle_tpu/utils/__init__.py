"""paddle.utils. reference: python/paddle/utils/ (deprecated.py,
lazy_import, download.py, unique_name.py via base, cpp_extension/).
"""

from __future__ import annotations

import functools
import importlib
import warnings

from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "require_version", "run_check",
           "cpp_extension", "unique_name"]


def deprecated(update_to="", since="", reason="", level=0):
    """reference: python/paddle/utils/deprecated.py."""
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            msg = f"API {func.__module__}.{func.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f". reason: {reason}"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return decorator


def try_import(module_name, err_msg=None):
    """reference: python/paddle/utils/lazy_import.py."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or
                          f"{module_name} is required: {e}") from e


def require_version(min_version, max_version=None):
    """reference: python/paddle/utils/__init__.py require_version."""
    from .. import __version__

    def to_tuple(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    cur = to_tuple(__version__)
    if to_tuple(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version and to_tuple(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > maximum {max_version}")


def run_check():
    """reference: python/paddle/utils/install_check.py run_check — verify the
    accelerator works by compiling and running a tiny matmul."""
    import jax
    import jax.numpy as jnp
    d = jax.devices()[0]
    x = jnp.ones((128, 128), jnp.float32)
    y = jax.jit(lambda a: a @ a)(x)
    y.block_until_ready()
    print(f"PaddleTPU works on {d.platform}:{d.device_kind if hasattr(d, 'device_kind') else d}. "
          f"matmul checksum {float(y.sum()):.0f}")
