"""Vision transforms (numpy-based host preprocessing).

reference: python/paddle/vision/transforms/.
"""

from __future__ import annotations

import numbers

import numpy as np

from ...framework.core import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor", "normalize", "resize", "hflip", "vflip"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _to_np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[None] if data_format == "CHW" else arr[..., None]
    elif data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _to_np(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    target = tuple(size) + arr.shape[2:]
    out = jax.image.resize(jnp.asarray(arr), target,
                           method=interpolation if interpolation != "nearest" else "nearest")
    return np.asarray(out)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def hflip(img):
    arr = _to_np(img)
    return arr[:, ::-1] if arr.ndim == 2 else arr[:, ::-1, :]


def vflip(img):
    arr = _to_np(img)
    return arr[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _to_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _to_np(img)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = _to_np(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[i:i + ch, j:j + cw]
                return resize(crop, self.size, self.interpolation)
        return resize(CenterCrop(min(h, w))(arr), self.size, self.interpolation)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1.5 else 1.0)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = _to_np(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad, constant_values=self.fill)
