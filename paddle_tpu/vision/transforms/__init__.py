"""Vision transforms (numpy-based host preprocessing).

reference: python/paddle/vision/transforms/.
"""

from __future__ import annotations

import numbers

import numpy as np

from ...framework.core import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor", "normalize", "resize", "hflip", "vflip"]


class BaseTransform:
    """reference: transforms.py BaseTransform (keys plumbing). All
    transform classes subclass it so isinstance checks from reference
    code keep working."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _to_np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[None] if data_format == "CHW" else arr[..., None]
    elif data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _to_np(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    target = tuple(size) + arr.shape[2:]
    out = jax.image.resize(jnp.asarray(arr), target,
                           method=interpolation if interpolation != "nearest" else "nearest")
    return np.asarray(out)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def hflip(img):
    arr = _to_np(img)
    return arr[:, ::-1] if arr.ndim == 2 else arr[:, ::-1, :]


def vflip(img):
    arr = _to_np(img)
    return arr[::-1]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _to_np(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _to_np(img)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = _to_np(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[i:i + ch, j:j + cw]
                return resize(crop, self.size, self.interpolation)
        return resize(CenterCrop(min(h, w))(arr), self.size, self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = _to_np(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad, constant_values=self.fill)


# ---------------------------------------------------------------------------
# functional transforms + the color/geometry transform classes
# (reference: vision/transforms/functional.py + transforms.py)
# ---------------------------------------------------------------------------

def crop(img, top, left, height, width):
    a = _to_np(img)
    return a[..., top:top + height, left:left + width] if a.ndim == 3 \
        and a.shape[0] in (1, 3) else a[top:top + height,
                                        left:left + width]


def center_crop(img, output_size):
    a = _to_np(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    h, w = a.shape[-3:-1] if a.ndim == 3 and a.shape[-1] in (1, 3) \
        else a.shape[-2:]
    if a.ndim == 3 and a.shape[-1] in (1, 3):  # HWC
        top = max((h - oh) // 2, 0)
        left = max((w - ow) // 2, 0)
        return a[top:top + oh, left:left + ow]
    h, w = a.shape[-2:]
    top = max((h - oh) // 2, 0)
    left = max((w - ow) // 2, 0)
    return a[..., top:top + oh, left:left + ow]


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _to_np(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    l, t, r, b = padding if len(padding) == 4 else \
        (padding[0], padding[1], padding[0], padding[1])
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    if a.ndim == 3 and a.shape[-1] in (1, 3):  # HWC
        return np.pad(a, ((t, b), (l, r), (0, 0)), mode, **kw)
    return np.pad(a, ((0, 0),) * (a.ndim - 2) + ((t, b), (l, r)), mode, **kw)


def adjust_brightness(img, brightness_factor):
    a = _to_np(img).astype(np.float32)
    hi = 255.0 if _to_np(img).dtype == np.uint8 else 1.0
    out = np.clip(a * brightness_factor, 0, hi)
    return out.astype(_to_np(img).dtype)


def adjust_contrast(img, contrast_factor):
    a = _to_np(img).astype(np.float32)
    hi = 255.0 if _to_np(img).dtype == np.uint8 else 1.0
    mean = a.mean()
    out = np.clip(mean + contrast_factor * (a - mean), 0, hi)
    return out.astype(_to_np(img).dtype)


def adjust_saturation(img, saturation_factor):
    a = _to_np(img).astype(np.float32)
    hi = 255.0 if _to_np(img).dtype == np.uint8 else 1.0
    if a.ndim == 3 and a.shape[-1] == 3:
        gray = a @ np.asarray([0.299, 0.587, 0.114], np.float32)
        gray = gray[..., None]
    else:
        gray = a
    out = np.clip(gray + saturation_factor * (a - gray), 0, hi)
    return out.astype(_to_np(img).dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round-trip."""
    a = _to_np(img)
    dt = a.dtype
    x = a.astype(np.float32) / (255.0 if dt == np.uint8 else 1.0)
    if x.ndim != 3 or x.shape[-1] != 3:
        return a
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = x.max(-1)
    mn = x.min(-1)
    d = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b)[m] / d[m]) % 6
    m = mx == g
    h[m] = (b - r)[m] / d[m] + 2
    m = mx == b
    h[m] = (r - g)[m] / d[m] + 4
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6).astype(np.int32) % 6
    f = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    choices = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)], 0)
    out = np.take_along_axis(choices, i[None, ..., None], 0)[0]
    out = out * (255.0 if dt == np.uint8 else 1.0)
    return np.clip(out, 0, 255 if dt == np.uint8 else 1.0).astype(dt)


def to_grayscale(img, num_output_channels=1):
    a = _to_np(img).astype(np.float32)
    if a.ndim == 3 and a.shape[-1] == 3:
        g = a @ np.asarray([0.299, 0.587, 0.114], np.float32)
    else:
        g = a.squeeze()
    out = np.repeat(g[..., None], num_output_channels, -1)
    return out.astype(_to_np(img).dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate around center (nearest-neighbor inverse mapping)."""
    a = _to_np(img)
    hwc = a.ndim == 3 and a.shape[-1] in (1, 3)
    if not hwc and a.ndim == 3:
        a = a.transpose(1, 2, 0)
    h, w = a.shape[:2]
    cy, cx = (center[1], center[0]) if center else ((h - 1) / 2,
                                                    (w - 1) / 2)
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sx = cos * (xx - cx) + sin * (yy - cy) + cx
    sy = -sin * (xx - cx) + cos * (yy - cy) + cy
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(a, fill)
    out[valid] = a[syi[valid], sxi[valid]]
    if not hwc and out.ndim == 3:
        out = out.transpose(2, 0, 1)
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp via inverse nearest mapping (reference F.affine)."""
    a = _to_np(img)
    hwc = a.ndim == 3 and a.shape[-1] in (1, 3)
    if not hwc and a.ndim == 3:
        a = a.transpose(1, 2, 0)
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    sx_sh, sy_sh = [np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix: T(center) R S Sh T(-center) + translate
    m = np.asarray([[np.cos(rad + sy_sh), -np.sin(rad + sx_sh)],
                    [np.sin(rad + sy_sh), np.cos(rad + sx_sh)]]) * scale
    minv = np.linalg.inv(m)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    pts = np.stack([xx - cx - translate[0], yy - cy - translate[1]])
    src = np.einsum("ij,jhw->ihw", minv, pts.astype(np.float64))
    sxi = np.round(src[0] + cx).astype(np.int64)
    syi = np.round(src[1] + cy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(a, fill)
    out[valid] = a[syi[valid], sxi[valid]]
    if not hwc and out.ndim == 3:
        out = out.transpose(2, 0, 1)
    return out


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """4-point perspective warp (reference F.perspective)."""
    a = _to_np(img)
    hwc = a.ndim == 3 and a.shape[-1] in (1, 3)
    if not hwc and a.ndim == 3:
        a = a.transpose(1, 2, 0)
    h, w = a.shape[:2]
    # solve homography end -> start (inverse map)
    A, bvec = [], []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        bvec.append(sx)
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec.append(sy)
    hcoef = np.linalg.lstsq(np.asarray(A, np.float64),
                            np.asarray(bvec, np.float64), rcond=None)[0]
    H = np.append(hcoef, 1.0).reshape(3, 3)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = H[2, 0] * xx + H[2, 1] * yy + H[2, 2]
    sx = (H[0, 0] * xx + H[0, 1] * yy + H[0, 2]) / den
    sy = (H[1, 0] * xx + H[1, 1] * yy + H[1, 2]) / den
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(a, fill)
    out[valid] = a[syi[valid], sxi[valid]]
    if not hwc and out.ndim == 3:
        out = out.transpose(2, 0, 1)
    return out


def erase(img, i, j, h, w, v, inplace=False):
    from ...framework.core import Tensor
    is_tensor = isinstance(img, Tensor)
    a = _to_np(img)
    out = a if inplace else a.copy()
    v = _to_np(v)
    if a.ndim == 3 and a.shape[-1] in (1, 3):
        out[i:i + h, j:j + w] = v
    else:
        out[..., i:i + h, j:j + w] = v
    if is_tensor:  # preserve the caller's container type (reference
        # contract: Tensor in -> Tensor out)
        res = Tensor(out)
        if inplace:
            img.set_value(res)
            return img
        return res
    return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = float(np.random.uniform(max(0, 1 - self.value), 1 + self.value))
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = float(np.random.uniform(max(0, 1 - self.value), 1 + self.value))
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = float(np.random.uniform(max(0, 1 - self.value), 1 + self.value))
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = float(np.random.uniform(-self.value, self.value))
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(4)
        for i in order:
            img = self.transforms[i](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = float(np.random.uniform(*self.degrees))
        return rotate(img, angle, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = _to_np(img)
        h, w = (a.shape[:2] if a.ndim == 3 and a.shape[-1] in (1, 3)
                else a.shape[-2:])
        angle = float(np.random.uniform(*self.degrees))
        tr = (0, 0)
        if self.translate:
            tr = (float(np.random.uniform(-self.translate[0],
                                          self.translate[0]) * w),
                  float(np.random.uniform(-self.translate[1],
                                          self.translate[1]) * h))
        sc = float(np.random.uniform(*self.scale)) if self.scale else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif np.isscalar(self.shear):
            sh = (float(np.random.uniform(-self.shear, self.shear)), 0.0)
        elif len(self.shear) == 2:     # [min_x, max_x]
            sh = (float(np.random.uniform(*self.shear)), 0.0)
        else:                          # [min_x, max_x, min_y, max_y]
            sh = (float(np.random.uniform(self.shear[0], self.shear[1])),
                  float(np.random.uniform(self.shear[2], self.shear[3])))
        return affine(img, angle, tr, sc, sh, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = _to_np(img)
        h, w = (a.shape[:2] if a.ndim == 3 and a.shape[-1] in (1, 3)
                else a.shape[-2:])
        d = self.distortion_scale
        dx = int(d * w / 2)
        dy = int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1))]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = _to_np(img)
        hwc = a.ndim == 3 and a.shape[-1] in (1, 3)
        h, w = (a.shape[:2] if hwc else a.shape[-2:])
        area = h * w * np.random.uniform(*self.scale)
        ar = np.random.uniform(*self.ratio)
        eh = min(int(round(np.sqrt(area * ar))), h)
        ew = min(int(round(np.sqrt(area / ar))), w)
        i = np.random.randint(0, h - eh + 1)
        j = np.random.randint(0, w - ew + 1)
        return erase(img, i, j, eh, ew, self.value)


__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
    "RandomHorizontalFlip", "RandomVerticalFlip", "RandomCrop",
    "CenterCrop", "RandomResizedCrop", "Transpose", "Pad", "ColorJitter",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "Grayscale", "RandomRotation", "RandomAffine",
    "RandomPerspective", "RandomErasing",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "crop",
    "center_crop", "pad", "adjust_brightness", "adjust_contrast",
    "adjust_saturation", "adjust_hue", "to_grayscale", "rotate", "affine",
    "perspective", "erase",
]
__all__ = [n for n in __all__ if n in dir()]
