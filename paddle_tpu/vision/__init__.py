"""paddle.vision. reference: python/paddle/vision/."""

from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401

# image backend registry (reference: vision/image.py)
_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file -> HWC uint8 array (PIL backend; cv2 unavailable
    in this environment)."""
    b = backend or _image_backend
    if b == "cv2":
        raise NotImplementedError("cv2 is not available in this build")
    import numpy as np
    try:
        from PIL import Image
        return np.asarray(Image.open(path))
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError("no image backend available") from e
