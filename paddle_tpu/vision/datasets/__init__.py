"""Vision datasets. reference: python/paddle/vision/datasets/.

Zero-egress environment: MNIST/Cifar generate deterministic synthetic data
when the real files are absent (download=False semantics preserved when
files exist locally in the standard paddle cache layout).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100"]


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 60000 if mode == "train" else 10000
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        else:
            # synthetic deterministic stand-in (no network egress): class
            # prototypes are split-independent so train→test generalizes
            proto_rng = np.random.RandomState(1234)
            base = proto_rng.rand(10, 28, 28)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = min(n, 2048)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            noise = rng.rand(n, 28, 28) * 0.3
            self.images = ((base[self.labels] * 0.7 + noise) * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        proto_rng = np.random.RandomState(1234)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 2048 if mode == "train" else 512
        self.num_classes = 10
        self.labels = rng.randint(0, self.num_classes, n).astype(np.int64)
        base = proto_rng.rand(self.num_classes, 32, 32, 3)
        self.images = ((base[self.labels] * 0.7 + rng.rand(n, 32, 32, 3) * 0.3)
                       * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend)
        self.num_classes = 100
        rng = np.random.RandomState(2)
        self.labels = rng.randint(0, 100, len(self.labels)).astype(np.int64)


class Flowers(Dataset):
    """reference: python/paddle/vision/datasets/flowers.py (102 classes).
    Synthetic deterministic stand-in (zero-egress environment)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        proto_rng = np.random.RandomState(4321)
        rng = np.random.RandomState({"train": 0, "valid": 1, "test": 2}.get(mode, 0))
        n = {"train": 1024, "valid": 256, "test": 256}.get(mode, 1024)
        self.num_classes = 102
        self.labels = rng.randint(0, self.num_classes, n).astype(np.int64)
        base = proto_rng.rand(self.num_classes, 64, 64, 3)
        self.images = ((base[self.labels] * 0.7 + rng.rand(n, 64, 64, 3) * 0.3)
                       * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """reference: python/paddle/vision/datasets/voc2012.py (segmentation).
    Synthetic: images + integer masks with the same spatial size."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        rng = np.random.RandomState({"train": 0, "valid": 1, "test": 2}.get(mode, 0))
        n = 256
        self.images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
        self.masks = rng.randint(0, 21, (n, 64, 64)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


__all__ += ["Flowers", "VOC2012"]


class DatasetFolder:
    """Generic folder-of-class-folders dataset.
    reference: vision/datasets/folder.py DatasetFolder."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or (
            ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".webp", ".npy")))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(base, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(exts)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        import numpy as np
        if path.endswith(".npy"):
            return np.load(path)
        from .. import image_load
        return image_load(path)

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Flat folder of images (no labels). reference: folder.py ImageFolder."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        exts = tuple(e.lower() for e in (extensions or (
            ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".webp", ".npy")))
        self.samples = []
        for base, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(base, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(exts)
                if ok:
                    self.samples.append(path)

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]
