"""Detection / vision ops.

reference: python/paddle/vision/ops.py (roi_align/roi_pool/psroi_pool CUDA
kernels, nms, deform_conv2d, yolo box+loss, prior_box, box_coder, FPN
proposal distribution, RPN proposal generation).

TPU design notes:
- RoI ops are bilinear-gather compositions (static shapes: boxes per image
  are padded/fixed counts, matching how detection models batch on TPU).
- NMS variants run eagerly on host (data-dependent output sizes — the same
  reason the reference runs them outside the hot graph at inference).
- deform_conv2d samples with the grid_sample machinery and runs the matmul
  on the MXU via an im2col einsum.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute

__all__ = [
    "yolo_loss", "yolo_box", "prior_box", "box_coder", "deform_conv2d",
    "DeformConv2D", "distribute_fpn_proposals", "generate_proposals",
    "read_file", "decode_jpeg", "roi_pool", "RoIPool", "psroi_pool",
    "PSRoIPool", "roi_align", "RoIAlign", "nms", "matrix_nms",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------

def _bilinear_at(feat, y, x):
    """feat: (C, H, W); y/x: (...) float coords. Returns (C, ...)."""
    c, h, w = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, h - 1)
    x0 = jnp.clip(jnp.floor(x), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy1 = jnp.clip(y - y0, 0.0, 1.0)
    wx1 = jnp.clip(x - x0, 0.0, 1.0)
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (wy0 * wx0) + v01 * (wy0 * wx1)
            + v10 * (wy1 * wx0) + v11 * (wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """reference: vision/ops.py roi_align (Mask R-CNN crop-and-resize).
    x: (N, C, H, W); boxes: (R, 4) [x1, y1, x2, y2]; boxes_num: (N,)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def f(feat, bx, bn):
        # map each roi to its image index from boxes_num
        img_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                             total_repeat_length=bx.shape[0])
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / oh
        bin_w = rw / ow
        # sample grid: (oh*sr, ow*sr) points per roi
        gy = (jnp.arange(oh * sr) + 0.5) / sr  # in bin units
        gx = (jnp.arange(ow * sr) + 0.5) / sr

        def one_roi(i):
            ys = y1[i] + gy * bin_h[i]              # (oh*sr,)
            xs = x1[i] + gx * bin_w[i]
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            vals = _bilinear_at(feat[img_idx[i]], yy, xx)  # (C, oh*sr, ow*sr)
            c = vals.shape[0]
            vals = vals.reshape(c, oh, sr, ow, sr)
            return vals.mean(axis=(2, 4))
        return jax.vmap(one_roi)(jnp.arange(bx.shape[0]))
    return execute(f, x, boxes, boxes_num, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference: vision/ops.py roi_pool (max pooling per bin)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        img_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                             total_repeat_length=bx.shape[0])
        x1 = jnp.round(bx[:, 0] * spatial_scale)
        y1 = jnp.round(bx[:, 1] * spatial_scale)
        x2 = jnp.maximum(jnp.round(bx[:, 2] * spatial_scale), x1 + 1)
        y2 = jnp.maximum(jnp.round(bx[:, 3] * spatial_scale), y1 + 1)
        bin_h = (y2 - y1) / oh
        bin_w = (x2 - x1) / ow
        h_im, w_im = feat.shape[2], feat.shape[3]
        # sample spacing <= 1 px even for the largest POSSIBLE bin (the
        # whole image): every integer pixel of every bin is visited, so the
        # bin max is exact
        sr_h = int(np.ceil(h_im / oh)) + 1
        sr_w = int(np.ceil(w_im / ow)) + 1
        gy = (jnp.arange(oh * sr_h) + 0.5) / sr_h
        gx = (jnp.arange(ow * sr_w) + 0.5) / sr_w

        def one_roi(i):
            # exact-bin max pooling reads INTEGER pixels (nearest), not
            # bilinear samples — a lone peak must survive exactly
            ys = jnp.clip(jnp.round(y1[i] + gy * bin_h[i] - 0.5), 0,
                          h_im - 1).astype(jnp.int32)
            xs = jnp.clip(jnp.round(x1[i] + gx * bin_w[i] - 0.5), 0,
                          w_im - 1).astype(jnp.int32)
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            vals = feat[img_idx[i]][:, yy, xx]
            c = vals.shape[0]
            vals = vals.reshape(c, oh, sr_h, ow, sr_w)
            return vals.max(axis=(2, 4))
        return jax.vmap(one_roi)(jnp.arange(bx.shape[0]))
    return execute(f, x, boxes, boxes_num, _name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN). Channels split into
    output_size^2 groups; bin (i, j) reads group i*ow+j."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        n, c, h, w = feat.shape
        assert c % (oh * ow) == 0, "channels must divide output_size^2"
        cg = c // (oh * ow)
        pooled = _arr(roi_align(Tensor(feat), Tensor(bx), Tensor(bn),
                                (oh, ow), spatial_scale, 2, False))
        # pooled: (R, C, oh, ow) -> pick position-sensitive group per bin
        r = pooled.shape[0]
        grouped = pooled.reshape(r, oh * ow, cg, oh, ow)
        bins = jnp.arange(oh * ow)
        out = grouped[:, bins, :, bins // ow, bins % ow]  # (oh*ow, R, cg)
        return jnp.moveaxis(out, 0, -1).reshape(r, cg, oh, ow)
    return execute(f, x, boxes, boxes_num, _name="psroi_pool")


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


# ---------------------------------------------------------------------------
# NMS family (host: data-dependent output sizes)
# ---------------------------------------------------------------------------

def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    union = areas[:, None] + areas[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS. reference: vision/ops.py nms (multiclass via offsets)."""
    b = np.asarray(_arr(boxes))
    s = np.asarray(_arr(scores)) if scores is not None else None
    if category_idxs is not None:
        # shift boxes per category so classes never suppress each other
        cat = np.asarray(_arr(category_idxs)).astype(np.int64)
        offset = (b.max() + 1.0) * cat[:, None]
        b = b + offset
    order = np.argsort(-s) if s is not None else np.arange(len(b))
    iou = _iou_matrix(b)
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft decay by max-IoU instead of hard
    suppression. reference: vision/ops.py matrix_nms."""
    bx = np.asarray(_arr(bboxes))
    sc = np.asarray(_arr(scores))
    n_img, n_cls = sc.shape[0], sc.shape[1]
    all_out, all_idx, rois_num = [], [], []
    for n in range(n_img):
        dets = []
        for c in range(n_cls):
            if c == background_label:
                continue
            mask = sc[n, c] > score_threshold
            idxs = np.nonzero(mask)[0]
            if idxs.size == 0:
                continue
            s_c = sc[n, c, idxs]
            order = np.argsort(-s_c)[:nms_top_k]
            idxs = idxs[order]
            s_c = s_c[order]
            b_c = bx[n, idxs]
            iou = _iou_matrix(b_c)
            iou = np.triu(iou, 1)
            max_iou = iou.max(axis=0, initial=0.0)  # vs higher-scored
            # decay_j = min over higher-scored i of f(iou_ij) / f(maxiou_i)
            # where maxiou_i is box i's own worst overlap with ITS superiors
            tri = np.triu(np.ones_like(iou, bool), 1)
            if use_gaussian:
                comp = np.exp(-(iou ** 2 - max_iou[:, None] ** 2)
                              / gaussian_sigma)
            else:
                comp = (1 - iou) / np.maximum(1 - max_iou[:, None], 1e-10)
            comp = np.where(tri, comp, 1.0)
            decay = np.minimum(comp.min(axis=0, initial=1.0), 1.0)
            s_dec = s_c * decay
            for j in range(len(idxs)):
                if s_dec[j] >= post_threshold:
                    dets.append((c, s_dec[j], *b_c[j], idxs[j]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        rois_num.append(len(dets))
        for d in dets:
            all_out.append(d[:-1])
            all_idx.append(d[-1])
    out = Tensor(jnp.asarray(np.asarray(all_out, np.float32).reshape(
        -1, 2 + bx.shape[-1])))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(all_idx, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int64))))
    return tuple(res) if len(res) > 1 else out


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (mask => v2). reference: vision/ops.py
    deform_conv2d. Sampling offsets feed the bilinear gather; the
    contraction runs as one einsum on the MXU."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            "deform_conv2d: groups/deformable_groups > 1 not supported")
    args = [x, offset, weight] + ([mask] if mask is not None else []) \
        + ([bias] if bias is not None else [])
    has_mask = mask is not None
    has_bias = bias is not None

    def f(a, off, w, *rest):
        n, cin, h, wdt = a.shape
        cout, _, kh, kw = w.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (wdt + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        pad_a = jnp.pad(a, ((0, 0), (0, 0), pd, pd))
        off = off.reshape(n, kh * kw, 2, oh, ow)
        off_y = off[:, :, 0]
        off_x = off[:, :, 1]

        def one(img, oy, ox, *more):
            k = 0
            cols = jnp.zeros((kh * kw, cin, oh, ow))
            for i in range(kh):
                for j in range(kw):
                    sy = (jnp.arange(oh) * st[0] + i * dl[0])[:, None] \
                        + oy[k]
                    sx = (jnp.arange(ow) * st[1] + j * dl[1])[None, :] \
                        + ox[k]
                    v = _bilinear_at(img, sy, sx)           # (cin, oh, ow)
                    if more:
                        v = v * more[0][k][None]
                    cols = cols.at[k].set(v)
                    k += 1
            return cols
        more = ()
        idx = 0
        if has_mask:
            m = rest[idx].reshape(n, kh * kw, oh, ow)
            idx += 1
        outs = []
        for b_i in range(n):
            margs = (m[b_i],) if has_mask else ()
            cols = one(pad_a[b_i], off_y[b_i], off_x[b_i], *margs)
            outs.append(cols)
        cols = jnp.stack(outs)                              # (n, khkw, cin, oh, ow)
        w2 = w.reshape(cout, cin, kh * kw)
        out = jnp.einsum("nkcij,ock->noij", cols, w2)
        if has_bias:
            out = out + rest[idx][None, :, None, None]
        return out
    return execute(f, *args, _name="deform_conv2d")


class DeformConv2D:
    """Layer wrapper owning weight/bias. reference: vision/ops.py
    DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from ..framework.core import Parameter
        from ..framework.random import next_key
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels * ks[0] * ks[1]
        bound = float(np.sqrt(6.0 / fan_in))
        self.weight = Parameter(jax.random.uniform(
            next_key(), (out_channels, in_channels) + ks, jnp.float32,
            -bound, bound))
        self.bias = None if bias_attr is False else Parameter(
            jnp.zeros((out_channels,), jnp.float32))
        self.stride = stride
        self.padding = padding
        self.dilation = dilation

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation, mask=mask)


# ---------------------------------------------------------------------------
# YOLO / anchors / proposals
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output into boxes+scores.
    reference: vision/ops.py yolo_box."""
    na = len(anchors) // 2
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))

    def f(feat, imsz):
        n, c, h, w = feat.shape
        iou_pred = None
        if iou_aware:
            # layout: first na channels are IoU logits, then na*(5+nc)
            iou_pred = jax.nn.sigmoid(feat[:, :na].reshape(n, na, h, w))
            feat = feat[:, na:]
        feat = feat.reshape(n, na, -1, h, w)
        tx, ty, tw, th = feat[:, :, 0], feat[:, :, 1], feat[:, :, 2], \
            feat[:, :, 3]
        obj = jax.nn.sigmoid(feat[:, :, 4])
        if iou_pred is not None:  # reference: conf = obj^(1-f) * iou^f
            obj = obj ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        cls = jax.nn.sigmoid(feat[:, :, 5:5 + class_num])
        gx = (jax.nn.sigmoid(tx) * scale_x_y - (scale_x_y - 1) / 2
              + jnp.arange(w)[None, None, None, :]) / w
        gy = (jax.nn.sigmoid(ty) * scale_x_y - (scale_x_y - 1) / 2
              + jnp.arange(h)[None, None, :, None]) / h
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        bw = jnp.exp(tw) * anc[None, :, None, None, 0] / input_w
        bh = jnp.exp(th) * anc[None, :, None, None, 1] / input_h
        im_h = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        im_w = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (gx - bw / 2) * im_w
        y1 = (gy - bh / 2) * im_h
        x2 = (gx + bw / 2) * im_w
        y2 = (gy + bh / 2) * im_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, im_w - 1)
            y1 = jnp.clip(y1, 0, im_h - 1)
            x2 = jnp.clip(x2, 0, im_w - 1)
            y2 = jnp.clip(y2, 0, im_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        score = (obj[..., None] * cls.transpose(0, 1, 3, 4, 2)).reshape(
            n, -1, class_num)
        keep = (obj.reshape(n, -1) > conf_thresh)[..., None]
        return boxes * keep, score * keep
    return execute(f, x, img_size, _name="yolo_box")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (coordinate + objectness + class).
    reference: vision/ops.py yolo_loss. Simplified: every gt is matched to
    its best anchor in `anchor_mask` at the cell containing its center."""
    na = len(anchor_mask)
    anc_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    anc = jnp.asarray(anc_all[np.asarray(anchor_mask)])

    def f(feat, gtb, gtl, *rest):
        n, c, h, w = feat.shape
        feat = feat.reshape(n, na, 5 + class_num, h, w)
        input_size = downsample_ratio * h
        tx = jax.nn.sigmoid(feat[:, :, 0])
        ty = jax.nn.sigmoid(feat[:, :, 1])
        obj_logit = feat[:, :, 4]
        cls_logit = feat[:, :, 5:]
        # build targets per gt box (center cell + best anchor by wh IoU)
        gx = gtb[..., 0] * w
        gy = gtb[..., 1] * h
        gw = gtb[..., 2]
        gh = gtb[..., 3]
        ci = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        wh = jnp.stack([gw * input_size, gh * input_size], -1)  # pixels
        inter = jnp.minimum(wh[..., None, 0], anc[None, None, :, 0]) * \
            jnp.minimum(wh[..., None, 1], anc[None, None, :, 1])
        union = wh[..., 0:1] * wh[..., 1:2] + anc[None, None, :, 0] \
            * anc[None, None, :, 1] - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)
        valid = (gw > 0) & (gh > 0)
        bidx = jnp.arange(n)[:, None] * jnp.ones_like(ci)
        # scatter targets
        t_obj = jnp.zeros((n, na, h, w))
        t_obj = t_obj.at[bidx, best_a, cj, ci].max(valid.astype(jnp.float32))
        sel = (bidx, best_a, cj, ci)
        lam = valid.astype(jnp.float32)
        lx = jnp.sum(lam * (tx[sel] - (gx - jnp.floor(gx))) ** 2)
        ly = jnp.sum(lam * (ty[sel] - (gy - jnp.floor(gy))) ** 2)
        tw_t = jnp.log(jnp.maximum(gw * input_size, 1e-9)
                       / jnp.maximum(anc[best_a][..., 0], 1e-9))
        th_t = jnp.log(jnp.maximum(gh * input_size, 1e-9)
                       / jnp.maximum(anc[best_a][..., 1], 1e-9))
        lw = jnp.sum(lam * (feat[:, :, 2][sel] - tw_t) ** 2)
        lh = jnp.sum(lam * (feat[:, :, 3][sel] - th_t) ** 2)
        bce = lambda lg, t: jnp.maximum(lg, 0) - lg * t \
            + jnp.log1p(jnp.exp(-jnp.abs(lg)))
        lobj = jnp.sum(bce(obj_logit, t_obj))
        t_cls = jax.nn.one_hot(gtl, class_num)
        if use_label_smooth:
            delta = 1.0 / class_num
            t_cls = t_cls * (1 - delta) + delta / class_num
        lcls = jnp.sum(lam[..., None]
                       * bce(jnp.moveaxis(cls_logit, 2, -1)[sel], t_cls))
        return (lx + ly + lw + lh + lobj + lcls) / n
    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None
                                    else [])
    return execute(f, *args, _name="yolo_loss")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes. reference: vision/ops.py prior_box."""
    def f(feat, img):
        h, w = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sh = steps[1] or ih / h
        sw = steps[0] or iw / w
        ars = list(aspect_ratios)
        if flip:
            ars = ars + [1.0 / a for a in ars if a != 1.0]
        boxes = []
        for ms in min_sizes:
            boxes.append((ms, ms))
            if max_sizes:
                for mx in max_sizes:
                    s = float(np.sqrt(ms * mx))
                    boxes.append((s, s))
            for a in ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                boxes.append((ms * float(np.sqrt(a)),
                              ms / float(np.sqrt(a))))
        nb = len(boxes)
        bw = jnp.asarray([b[0] for b in boxes]) / iw
        bh = jnp.asarray([b[1] for b in boxes]) / ih
        cx = (jnp.arange(w) + offset) * sw / iw
        cy = (jnp.arange(h) + offset) * sh / ih
        gcx, gcy = jnp.meshgrid(cx, cy)
        out = jnp.stack([
            gcx[..., None] - bw / 2, gcy[..., None] - bh / 2,
            gcx[..., None] + bw / 2, gcy[..., None] + bh / 2], -1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance), (h, w, nb, 4))
        return out, var
    return execute(f, input, image, _name="prior_box")


def box_coder(prior_box_t, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors. reference: vision/ops.py
    box_coder."""
    args = [prior_box_t, target_box] + (
        [prior_box_var] if isinstance(prior_box_var, Tensor) else [])
    var_const = None if isinstance(prior_box_var, Tensor) else \
        jnp.asarray(prior_box_var if prior_box_var is not None
                    else [1.0, 1.0, 1.0, 1.0])

    def f(pb, tb, *rest):
        var = rest[0] if rest else var_const
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            dx = (tcx - pcx) / pw
            dy = (tcy - pcy) / ph
            dw = jnp.log(jnp.maximum(tw / pw, 1e-10))
            dh = jnp.log(jnp.maximum(th / ph, 1e-10))
            enc = jnp.stack([dx, dy, dw, dh], -1)
            return enc / var.reshape(-1, 4) if var.ndim else enc / var
        # decode
        v = var if var is not None else jnp.ones((4,))
        d = tb * (v.reshape(-1, 4) if v.ndim > 1 else v)
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        ww = jnp.exp(d[..., 2]) * pw
        hh = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - ww / 2, cy - hh / 2,
                          cx + ww / 2 - norm, cy + hh / 2 - norm], -1)
    return execute(f, *args, _name="box_coder")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (FPN paper eq. 1).
    reference: vision/ops.py distribute_fpn_proposals. Host op (ragged)."""
    rois = np.asarray(_arr(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
                    * np.maximum(rois[:, 3] - rois[:, 1] + off, 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, restore = [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        restore.extend(idx.tolist())
    restore_ind = np.empty(len(rois), np.int64)
    restore_ind[np.asarray(restore, np.int64)] = np.arange(len(rois))
    result = [outs, Tensor(jnp.asarray(restore_ind.reshape(-1, 1)))]
    if rois_num is not None:
        nums = [Tensor(jnp.asarray(np.asarray([len(np.nonzero(lvl == l)[0])],
                                              np.int32)))
                for l in range(min_level, max_level + 1)]
        result.append(nums)
    return tuple(result)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation: decode anchors, clip, filter, NMS.
    reference: vision/ops.py generate_proposals. Host op (ragged)."""
    sc = np.asarray(_arr(scores))
    bd = np.asarray(_arr(bbox_deltas))
    im = np.asarray(_arr(img_size))
    an = np.asarray(_arr(anchors)).reshape(-1, 4)
    va = np.asarray(_arr(variances)).reshape(-1, 4)
    n = sc.shape[0]
    all_rois, all_probs, rois_num = [], [], []
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = bd[b].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], va[order]
        off = 1.0 if pixel_offset else 0.0
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        ww = np.exp(np.minimum(v[:, 2] * d[:, 2], 10)) * aw
        hh = np.exp(np.minimum(v[:, 3] * d[:, 3], 10)) * ah
        boxes = np.stack([cx - ww / 2, cy - hh / 2,
                          cx + ww / 2 - off, cy + hh / 2 - off], -1)
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, im[b, 1] - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, im[b, 0] - off)
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                   & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep_sz], s[keep_sz]
        keep = np.asarray(nms(Tensor(jnp.asarray(boxes)),
                              iou_threshold=nms_thresh,
                              scores=Tensor(jnp.asarray(s)))._data)
        keep = keep[:post_nms_top_n]
        all_rois.append(boxes[keep])
        all_probs.append(s[keep])
        rois_num.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              .astype(np.float32)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               .astype(np.float32)))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(rois_num,
                                                          np.int32)))
    return rois, probs


# ---------------------------------------------------------------------------
# file IO
# ---------------------------------------------------------------------------

def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    import io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError("decode_jpeg needs PIL") from e
    raw = bytes(np.asarray(_arr(x)).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
