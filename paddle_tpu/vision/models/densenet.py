"""DenseNet. reference: python/paddle/vision/models/densenet.py."""

from __future__ import annotations

from ... import nn
from ...tensor import manipulation as _man

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_ARCH = {
    121: (32, [6, 12, 24, 16], 64),
    161: (48, [6, 12, 36, 24], 96),
    169: (32, [6, 12, 32, 32], 64),
    201: (32, [6, 12, 48, 32], 64),
    264: (32, [6, 12, 64, 48], 64),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return _man.concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(in_c + i * growth_rate, growth_rate, bn_size, dropout)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """reference: python/paddle/vision/models/densenet.py DenseNet."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        growth_rate, block_cfg, num_init = _ARCH[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        ch = num_init
        for i, n in enumerate(block_cfg):
            blocks.append(_DenseBlock(n, ch, growth_rate, bn_size, dropout))
            ch += n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(layers=121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(layers=161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(layers=169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(layers=201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(layers=264, **kwargs)
