"""AlexNet, SqueezeNet, ShuffleNetV2. reference:
python/paddle/vision/models/{alexnet.py, squeezenet.py, shufflenetv2.py}.
"""

from __future__ import annotations

from ... import nn
from ...tensor import manipulation as _man

__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "ShuffleNetV2", "shufflenet_v2_x0_25",
           "shufflenet_v2_x0_33", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


class AlexNet(nn.Layer):
    """reference: python/paddle/vision/models/alexnet.py AlexNet."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(dropout), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return _man.concat([self.relu(self.expand1(x)),
                            self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    """reference: python/paddle/vision/models/squeezenet.py SqueezeNet."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.final_conv = nn.Conv2D(512, num_classes, 1)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.5)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.relu(self.final_conv(self.dropout(x)))
        if self.with_pool:
            x = self.pool(x)
            if self.num_classes > 0:
                x = x.flatten(1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = _man.reshape(x, [n, groups, c // groups, h, w])
    x = _man.transpose(x, [0, 2, 1, 3, 4])
    return _man.reshape(x, [n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act_layer())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer())

    def forward(self, x):
        if self.stride > 1:
            out = _man.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1 = x[:, :c]
            x2 = x[:, c:]
            out = _man.concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """reference: python/paddle/vision/models/shufflenetv2.py ShuffleNetV2."""

    _STAGE_OUT = {
        0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
        0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
        1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048]}

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = self._STAGE_OUT[scale]
        repeats = [4, 8, 4]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, cfg[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(cfg[0]), act_layer())
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = cfg[0]
        for stage_i, rep in enumerate(repeats):
            out_c = cfg[stage_i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            units += [_ShuffleUnit(out_c, out_c, 1, act) for _ in range(rep - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, cfg[-1], 1, bias_attr=False),
            nn.BatchNorm2D(cfg[-1]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(cfg[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
