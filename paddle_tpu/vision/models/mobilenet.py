"""MobileNet V1/V2/V3. reference: python/paddle/vision/models/
{mobilenetv1.py, mobilenetv2.py, mobilenetv3.py}.

Original TPU-oriented implementations — depthwise convs lower to XLA grouped
conv, which Mosaic maps to the MXU with channel tiling.
"""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale):
        super().__init__()
        c1 = int(out_c1 * scale)
        c2 = int(out_c2 * scale)
        self.dw = ConvBNLayer(in_c, c1, 3, stride=stride, padding=1, groups=in_c)
        self.pw = ConvBNLayer(c1, c2, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """reference: python/paddle/vision/models/mobilenetv1.py MobileNetV1."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2, padding=1)
        cfg = [  # in, c1, c2, stride
            (s(32), 32, 64, 1), (s(64), 64, 128, 2), (s(128), 128, 128, 1),
            (s(128), 128, 256, 2), (s(256), 256, 256, 1), (s(256), 256, 512, 2),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1), (s(512), 512, 1024, 2),
            (s(1024), 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, c1, c2, st, scale) for i, c1, c2, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        hidden = int(round(inp * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act=nn.ReLU6))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act=nn.ReLU6),
            ConvBNLayer(hidden, oup, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    """reference: python/paddle/vision/models/mobilenetv2.py MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        feats = [ConvBNLayer(3, in_c, 3, stride=2, padding=1, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = _make_divisible(1280 * max(1.0, scale))
        feats.append(ConvBNLayer(in_c, self.last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(self.last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class SqueezeExcitation(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers.append(ConvBNLayer(in_c, exp_c, 1, act=act_layer))
        layers.append(ConvBNLayer(exp_c, exp_c, kernel, stride=stride,
                                  padding=kernel // 2, groups=exp_c,
                                  act=act_layer))
        if use_se:
            layers.append(SqueezeExcitation(exp_c, _make_divisible(exp_c // 4)))
        layers.append(ConvBNLayer(exp_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        feats = [ConvBNLayer(3, in_c, 3, stride=2, padding=1, act=nn.Hardswish)]
        for k, exp, c, se, act, s in cfg:
            out_c = _make_divisible(c * scale)
            exp_c = _make_divisible(exp * scale)
            feats.append(_V3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        exp_out = _make_divisible(last_exp * scale)
        feats.append(ConvBNLayer(in_c, exp_out, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(exp_out, last_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    """reference: python/paddle/vision/models/mobilenetv3.py MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [  # k, exp, c, se, act, s
            (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
            (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
            (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
            (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
            (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
            (5, 576, 96, True, "hardswish", 1)]
        super().__init__(cfg, 576, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    """reference: python/paddle/vision/models/mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
            (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
            (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
            (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
            (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
            (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
            (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
            (5, 960, 160, True, "hardswish", 1)]
        super().__init__(cfg, 960, 1280, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
