"""Dtype registry. reference: paddle/phi/common/data_type.h + python/paddle/framework/dtype.py.

TPU-first: bfloat16 is the native accelerator dtype (MXU) — float64 is
discouraged (soft-emulated on TPU); default float dtype is float32.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
# fp8 storage dtypes (reference: python/paddle/framework/dtype.py
# FP8_E4M3FN/FP8_E5M2) — real ml_dtypes types; TPU computes via upcast,
# nn.quant.format rounds through them for serialization-exact fake quant
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

NAME2DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
    "fp32": jnp.float32,
    "fp64": jnp.float64,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
    # short serving-config spellings (inference kv_cache_dtype et al.)
    "fp8_e4m3": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
}

_DEFAULT_FLOAT = [jnp.float32]


def set_default_dtype(d):
    _DEFAULT_FLOAT[0] = convert_dtype(d)


def get_default_dtype():
    return dtype_name(_DEFAULT_FLOAT[0])


def convert_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return NAME2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"unknown dtype {dtype!r}")
    return np.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name if dtype != jnp.bfloat16 else "bfloat16"


def asarray_default(data):
    """Convert python/numpy data with paddle-like defaults: python floats ->
    default float dtype; numpy arrays keep their dtype (float64 preserved for
    numeric-check parity on CPU; cast on demand for TPU)."""
    if isinstance(data, (bool, np.bool_)):
        return jnp.asarray(data, dtype=jnp.bool_)
    if isinstance(data, (int, np.integer)):
        return jnp.asarray(data, dtype=jnp.int64)
    if isinstance(data, (float, np.floating)):
        return jnp.asarray(data, dtype=_DEFAULT_FLOAT[0])
    if isinstance(data, (list, tuple)):
        a = np.asarray(data)
        if a.dtype == np.float64:
            a = a.astype(np.dtype(_DEFAULT_FLOAT[0]))
        if a.dtype == np.int32:
            pass
        return jnp.asarray(a)
    return jnp.asarray(data)


def is_floating(dtype):
    return jnp.issubdtype(dtype, jnp.floating)


def is_integer(dtype):
    return jnp.issubdtype(dtype, jnp.integer)
