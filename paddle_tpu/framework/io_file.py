"""paddle.save / paddle.load. reference: python/paddle/framework/io.py:773.

State dicts are pickled with tensors converted to numpy (device-independent,
works for TPU arrays)."""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core import Tensor

__all__ = ["save", "load"]


def _to_savable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_savable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_savable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_savable(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name")
            return t
        return {k: _from_savable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_savable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_savable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _from_savable(pickle.load(f))
