"""Device/place API. reference: python/paddle/device/__init__.py, paddle/phi/common/place.h.

On TPU there is one first-class device family; Place collapses to a thin
wrapper over jax.Device. CUDAPlace/XPUPlace aliases exist for API parity and
map to the accelerator if present, else CPU.
"""

from __future__ import annotations

import jax

_current_device = None


class Place:
    def __init__(self, kind: str, device_id: int = 0):
        self.kind = kind
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.device_id) == (
            other.kind,
            other.device_id,
        )


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


class CUDAPlace(Place):  # parity alias: maps to the accelerator
    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


def set_device(device: str):
    """paddle.set_device('tpu') / ('cpu') / ('tpu:0')"""
    global _current_device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(name, name)
    devs = jax.devices() if name != "cpu" else jax.devices("cpu")
    if name not in ("cpu",):
        accel = [d for d in devs if d.platform != "cpu"]
        devs = accel or devs
    _current_device = devs[min(idx, len(devs) - 1)]
    jax.config.update("jax_default_device", _current_device)
    return get_device()


def get_device() -> str:
    d = _current_device or jax.devices()[0]
    plat = "tpu" if d.platform not in ("cpu",) else "cpu"
    return f"{plat}:{d.id}" if plat != "cpu" else "cpu"


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def cuda_device_count() -> int:
    return 0
