"""Graph-break prefix compilation for to_static (SOT partial-graph analog).

When a to_static trace hits data-dependent Python control flow, round-3
behavior was whole-function eager fallback. This module instead runs the
function in *staged* mode: every execute() op is deferred into a DAG of
StagedNodes, and the first concretization point (bool()/int()/float()/
item()/numpy() on a staged tensor — the graph break) flushes the
accumulated prefix as ONE jit-compiled XLA computation. Execution then
continues staging, so a function with K breaks runs as K+1 compiled
segments instead of per-op eager dispatches.

reference analog: python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py — SOT compiles the partial graph up to the break and
stitches eager execution after it.

The flushed prefix goes through framework.core.execute() as a single op,
so it lands on the autograd tape as one vjp node — backward through a
broken function stays correct and fully compiled per segment.
"""

from __future__ import annotations

import weakref
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


class StagedBox:
    """Placeholder living in Tensor._data while the op that produces the
    value is deferred. Carries the aval so shape/dtype-dependent Python
    code proceeds without materializing."""

    __slots__ = ("aval", "scope", "real", "owner", "__weakref__")

    def __init__(self, aval, scope):
        self.aval = aval
        self.scope = scope
        self.real = None
        self.owner = None  # weakref to the Tensor owning this box

    # -- aval surface (no materialization) ---------------------------------
    @property
    def shape(self):
        return self.aval.shape if self.real is None else self.real.shape

    @property
    def dtype(self):
        return self.aval.dtype if self.real is None else self.real.dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= int(s)
        return n

    def devices(self):
        self._materialize()
        return self.real.devices()

    # -- concretization = graph break --------------------------------------
    def _materialize(self):
        if self.real is None:
            self.scope.flush()
        assert self.real is not None
        return self.real

    def __array__(self, dtype=None):
        a = np.asarray(self._materialize())
        return a.astype(dtype) if dtype is not None else a

    def __bool__(self):
        return bool(self._materialize())

    def __int__(self):
        return int(self._materialize())

    def __float__(self):
        return float(self._materialize())

    def __index__(self):
        return int(self._materialize())

    def item(self, *args):
        return self._materialize().item(*args)

    def tolist(self):
        return np.asarray(self._materialize()).tolist()

    def __jax_array__(self):
        return self._materialize()

    def astype(self, dtype):
        return self._materialize().astype(dtype)

    def reshape(self, *a, **k):
        return self._materialize().reshape(*a, **k)

    def __getattr__(self, name):
        # unanticipated jax.Array attribute: materialize and delegate
        return getattr(self._materialize(), name)


class StagedNode:
    __slots__ = ("f", "kwargs", "name", "parents", "out_boxes",
                 "out_treedef", "amp_hook")

    def __init__(self, f, kwargs, name, parents):
        self.f = f
        self.kwargs = kwargs
        self.name = name
        self.parents = parents  # list of StagedBox | ('leaf', Tensor) |
        #                         ('const', raw)
        self.out_boxes = []
        self.out_treedef = None
        self.amp_hook = None    # amp cast hook captured at stage time

    def run(self, args):
        """Apply the captured per-op AMP cast (if any), then the op."""
        if self.amp_hook is not None:
            args = self.amp_hook(self.name, list(args))
        return self.f(*args, **self.kwargs)


def _make_run(f, kwargs, amp_hook, name):
    """StagedNode.run detached from the node, so caching it does not
    retain the node's parents/out_boxes (see flush())."""
    def run(args):
        if amp_hook is not None:
            args = amp_hook(name, list(args))
        return f(*args, **kwargs)
    return run


# host arrays up to this many elements key by CONTENT, so fresh-per-call
# numpy consts (np scalars, small index/shape arrays) still hit the cache
_SMALL_ARRAY = 4096


def _const_summary(v, id_objs):
    """Hashable key for a closure cell / static kwarg / const parent.

    Scalars key by (type, value) — 1, 1.0 and True hash equal in Python,
    and a type-blind key would replay a segment with the wrong-typed
    constant baked in. Small host (numpy) values key by content. Anything
    else array-like, and opaque objects, key by id and are appended to
    `id_objs`: flush() attaches a weakref-evict callback (or a strong pin
    when the type is not weakref-able) to the cache entry, so a gc'd id
    can never be recycled into a fake match against a stale compiled
    segment. repr() is never used — numpy summarizes large arrays, so
    distinct consts can share a truncated repr."""
    if isinstance(v, (float, complex)):
        # repr keeps the sign of zero: 0.0 and -0.0 compare/hash equal but
        # bake differently (copysign, atan2, 1/x)
        return (type(v).__name__, repr(v))
    if isinstance(v, (bool, int, str, bytes, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,
                tuple(_const_summary(e, id_objs) for e in v))
    if isinstance(v, dict):
        return ("dict", tuple(sorted(
            (repr(k), _const_summary(e, id_objs)) for k, e in v.items())))
    if isinstance(v, (set, frozenset)):
        return ("set", tuple(sorted(
            repr(_const_summary(e, id_objs)) for e in v)))
    if (isinstance(v, (np.ndarray, np.generic))
            and v.size <= _SMALL_ARRAY and v.dtype != object):
        # dtype=object is excluded: its tobytes() is raw element POINTERS,
        # which would resurrect the recycled-id fake-match this key avoids
        return ("arrc", tuple(np.shape(v)), str(v.dtype), v.tobytes())
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        id_objs.append(v)
        return ("arr", tuple(v.shape), str(v.dtype), id(v))
    if callable(v):
        code = getattr(v, "__code__", None)
        if code is None:
            id_objs.append(v)
            code = id(v)
        return ("fn", code, _cell_summary(v, id_objs))
    id_objs.append(v)
    return ("obj", type(v).__name__, id(v))


def _cell_summary(f, id_objs):
    """Key for a function's closure contents (see _const_summary)."""
    cells = getattr(f, "__closure__", None) or ()
    return tuple(_const_summary(c.cell_contents, id_objs) for c in cells)


def _kw_summary(kw, id_objs):
    return tuple(sorted((k, _const_summary(v, id_objs))
                        for k, v in kw.items()))


class StagingScope:
    """Active deferred-execution region. core.execute() routes ops here
    while `active`; flush() compiles+runs the pending prefix."""

    def __init__(self, jit_cache=None):
        self.pending: list[StagedNode] = []
        self.active = False
        self.jit_cache = jit_cache if jit_cache is not None else {}
        self.segments = 0          # compiled segments so far (telemetry)

    # -- context manager ----------------------------------------------------
    def __enter__(self):
        from . import core as _core
        self._prev = _core._STAGING_SCOPE
        _core._STAGING_SCOPE = self
        self.active = True
        return self

    def __exit__(self, exc_type, *exc):
        from . import core as _core
        try:
            if exc_type is None:
                self.flush()   # returned tensors must be real
        finally:
            self.active = False
            _core._STAGING_SCOPE = self._prev
        return False

    # -- staging ------------------------------------------------------------
    def stage(self, f, inputs, name, static_kwargs):
        from . import core as _core
        from .core import Tensor, _GRAD_ENABLED
        # per-op hooks still apply in staged mode: the op observer fires at
        # stage time (same count as eager), and the CURRENT amp cast hook
        # is captured per node so replay applies O1/O2 casts per op inside
        # the compiled segment (review r4: staged mode silently dropped AMP)
        amp_hook = _core._amp_cast_hook
        if _core._op_observer_hook is not None:
            try:
                _core._op_observer_hook(
                    name or getattr(f, "__name__", "op"),
                    [x._data for x in inputs if isinstance(x, Tensor)])
            except Exception:
                pass
        parents = []
        avals = []
        any_diff = False
        for x in inputs:
            if isinstance(x, Tensor):
                d = x._data
                if isinstance(d, StagedBox) and d.real is None:
                    parents.append(d)
                    avals.append(jax.ShapeDtypeStruct(d.shape, d.dtype))
                else:
                    arr = d.real if isinstance(d, StagedBox) else d
                    parents.append(("leaf", x))
                    avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
                if (_GRAD_ENABLED and not x.stop_gradient
                        and jnp.issubdtype(jnp.result_type(d.dtype),
                                           jnp.inexact)):
                    any_diff = True
            else:
                parents.append(("const", x))
                avals.append(x)
        node = StagedNode(f, dict(static_kwargs), name or
                          getattr(f, "__name__", "op"), parents)
        node.amp_hook = amp_hook
        fwd = node.run  # applies the captured amp cast, then f
        out_aval = jax.eval_shape(lambda *a: fwd(a), *avals)
        flat_avals, treedef = jax.tree_util.tree_flatten(out_aval)
        node.out_treedef = treedef
        outs = []
        for av in flat_avals:
            box = StagedBox(av, self)
            node.out_boxes.append(box)
            t = Tensor.__new__(Tensor)
            t._data = box
            t._grad = None
            t._node = None
            t.stop_gradient = not any_diff
            t.persistable = False
            t.name = None
            box.owner = weakref.ref(t)
            outs.append(t)
        self.pending.append(node)
        return jax.tree_util.tree_unflatten(treedef, outs)

    # -- flush: compile + run the pending prefix ----------------------------
    @staticmethod
    def _fingerprint(nodes, box_slot, leaf_ids, id_objs):
        """Structural key for reusing a segment's compiled replay across
        calls. Box parents key by their SLOT in the segment (stable across
        calls); fresh per-call closure DEVICE arrays miss by id and
        recompile (host arrays content-key, see _const_summary). Every
        id-keyed object lands in `id_objs` so flush() can tie the cache
        entry's lifetime to theirs."""
        parts = []
        for node in nodes:
            pdesc = []
            for p in node.parents:
                if isinstance(p, StagedBox):
                    pdesc.append(("box", box_slot[id(p)]))
                elif p[0] == "leaf":
                    d = p[1]._data
                    arr = d.real if isinstance(d, StagedBox) else d
                    pdesc.append(("leaf", leaf_ids[id(p[1])],
                                  tuple(arr.shape), str(arr.dtype),
                                  p[1].stop_gradient))
                else:
                    pdesc.append(("const", _const_summary(p[1], id_objs)))
            code = getattr(node.f, "__code__", None)
            if code is None:
                id_objs.append(node.f)
                code = id(node.f)
            if node.amp_hook is not None:
                id_objs.append(node.amp_hook)
            parts.append((node.name, code,
                          _cell_summary(node.f, id_objs),
                          _kw_summary(node.kwargs, id_objs),
                          None if node.amp_hook is None else id(node.amp_hook),
                          tuple(pdesc),
                          tuple((tuple(b.aval.shape), str(b.aval.dtype))
                                for b in node.out_boxes)))
        return tuple(parts)

    def flush(self):
        from .core import execute
        if not self.pending:
            return
        nodes, self.pending = self.pending, []
        self.segments += 1

        # ordered unique leaf tensors feeding this segment
        leaf_tensors: list = []
        leaf_ids = {}
        for node in nodes:
            for p in node.parents:
                if isinstance(p, tuple) and p[0] == "leaf":
                    t = p[1]
                    if id(t) not in leaf_ids:
                        leaf_ids[id(t)] = len(leaf_tensors)
                        leaf_tensors.append(t)

        box_slot = {}
        all_boxes = []
        for node in nodes:
            for b in node.out_boxes:
                box_slot[id(b)] = len(all_boxes)
                all_boxes.append(b)

        # slot-resolve every parent NOW so the cached replay closes over a
        # lightweight spec — never over Tensors or result arrays (review
        # r4: caching (replay, nodes) pinned a whole call's activations
        # for the StaticFunction's lifetime)
        spec = []   # per node: (run, [("env",slot)|("leaf",i)|("const",v)], out_slots)
        for node in nodes:
            pdesc = []
            for p in node.parents:
                if isinstance(p, StagedBox):
                    pdesc.append(("env", box_slot[id(p)]))
                elif p[0] == "leaf":
                    pdesc.append(("leaf", leaf_ids[id(p[1])]))
                else:
                    pdesc.append(("const", p[1]))
            # a detached run closure, NOT node.run: the cached jitted replay
            # keeps spec alive, and the bound method would drag node.parents
            # (a whole call's leaf Tensors) and node.out_boxes (the segment's
            # outputs) along with it for the cache entry's lifetime
            spec.append((_make_run(node.f, node.kwargs, node.amp_hook,
                                   node.name),
                         pdesc,
                         [box_slot[id(b)] for b in node.out_boxes]))
        n_boxes = len(all_boxes)

        def replay(*leaf_arrays):
            # a box parent always belongs to THIS segment: flush drains all
            # pending nodes, so anything staged later sees only real data
            env: dict[int, Any] = {}
            for run, pdesc, out_slots in spec:
                args = [env[v] if kind == "env"
                        else leaf_arrays[v] if kind == "leaf" else v
                        for kind, v in pdesc]
                out = run(args)   # per-op AMP cast + f
                for slot, arr in zip(out_slots,
                                     jax.tree_util.tree_leaves(out)):
                    env[slot] = arr
            return tuple(env[i] for i in range(n_boxes))

        id_objs: list = []
        key = self._fingerprint(nodes, box_slot, leaf_ids, id_objs)
        entry = self.jit_cache.get(key)
        if entry is None:
            if len(self.jit_cache) >= 64:
                # bounded: per-call closure device arrays (id-keyed) would
                # otherwise grow one never-hit entry per step
                self.jit_cache.pop(next(iter(self.jit_cache)))
            # Tie the entry's lifetime to every id-keyed object in its key:
            # when one dies, evict, so a recycled id can never fake-match a
            # stale compiled replay — without strongly retaining per-call
            # arrays (which can be whole activations) until FIFO eviction.
            cache = self.jit_cache
            refs = []
            for obj in id_objs:
                try:
                    refs.append(weakref.ref(
                        obj, lambda _r, k=key, c=cache: c.pop(k, None)))
                except TypeError:
                    refs.append(obj)   # not weakref-able: pin strongly
            entry = (jax.jit(replay), refs)
            self.jit_cache[key] = entry
        jitted = entry[0]

        # run OUTSIDE staging so the segment lands on the tape as one node
        self.active = False
        try:
            try:
                outs = execute(jitted, *leaf_tensors, _name="staged_prefix")
            except Exception:
                # op not jit-traceable (host callback etc.): replay eagerly
                self.jit_cache.pop(key, None)
                outs = execute(replay, *leaf_tensors, _name="staged_prefix")
        finally:
            self.active = True
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        for b, out_t in zip(all_boxes, outs):
            b.real = out_t._data
            owner = b.owner() if b.owner is not None else None
            if owner is not None:
                owner._rebind(out_t)
