"""Global RNG state over jax PRNG keys.

reference: paddle.seed (python/paddle/framework/random.py) and the TP-aware
RNG tracker (python/paddle/distributed/fleet/layers/mpu/random.py
get_rng_state_tracker). Paddle's stateful generators map onto a host-side
counter folded into a base key — inside a `to_static` trace the key comes
from a traced input so compiled steps get fresh randomness per call without
retracing.
"""

from __future__ import annotations

import contextlib

import jax


class _GlobalRNG:
    """Lazy: the base key is materialized on first use, NOT at import —
    creating an array at import time would initialize the jax backend before
    the application can pick a platform (e.g. the launcher choosing CPU)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._base = None
        self.counter = 0
        # trace mode: stack of (traced_key, [counter]) installed by jit.to_static
        self.trace_stack = []

    @property
    def base(self):
        if self._base is None:
            self._base = jax.random.key(self._seed)
        return self._base

    @base.setter
    def base(self, v):
        self._base = v

    def seed(self, s: int):
        self._seed = int(s)
        self._base = jax.random.key(self._seed)
        self.counter = 0

    def next_key(self):
        if self.trace_stack:
            key, ctr = self.trace_stack[-1]
            ctr[0] += 1
            return jax.random.fold_in(key, ctr[0])
        self.counter += 1
        return jax.random.fold_in(self.base, self.counter)

    @contextlib.contextmanager
    def trace_scope(self, traced_key):
        self.trace_stack.append((traced_key, [0]))
        try:
            yield
        finally:
            self.trace_stack.pop()


_global_rng = _GlobalRNG()


def seed(s: int):
    """paddle.seed"""
    _global_rng.seed(int(s))
    return _global_rng


def next_key():
    return _global_rng.next_key()


def get_rng_state():
    return (_global_rng.base, _global_rng.counter)


def set_rng_state(state):
    _global_rng.base, _global_rng.counter = state


class RNGStatesTracker:
    """Named RNG states for TP determinism.

    reference: python/paddle/distributed/fleet/layers/mpu/random.py:RNGStatesTracker —
    used so dropout inside tensor-parallel regions draws per-rank-unique or
    replicated noise depending on the named state.
    """

    def __init__(self):
        self.states = {}

    def add(self, name, seed_):
        if name in self.states:
            raise ValueError(f"state {name} already exists")
        self.states[name] = _GlobalRNG(int(seed_))

    def reset(self):
        self.states = {}

    @contextlib.contextmanager
    def rng_state(self, name="global_seed"):
        if name not in self.states:
            self.add(name, hash(name) % (2**31))
        global _global_rng
        prev = _global_rng
        _global_rng = self.states[name]
        try:
            yield
        finally:
            _global_rng = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker
