from . import dtypes, flags, random, device
from .core import (
    Tensor,
    Parameter,
    EagerParamBase,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
    execute,
    to_tensor,
)
