"""Runtime flag registry.

reference: paddle/common/flags.h:38-89 (PD_DEFINE_* macros),
paddle/common/flags_native.cc (native parser), surfaced as
paddle.set_flags/get_flags (python/paddle/base/framework.py:132,157).

TPU-native: most of the ~190 reference flags control CUDA allocators,
cuDNN autotune, NCCL — irrelevant under XLA. We keep the registry shape
(env-var override `FLAGS_*`, set/get API) and define the flags that
matter on TPU.
"""

from __future__ import annotations

import os
from typing import Any

_REGISTRY: dict[str, dict] = {}


def define_flag(name: str, default: Any, help_: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = {"value": value, "default": default, "help": help_}
    return value


def set_flags(flags: dict):
    """paddle.set_flags"""
    for k, v in flags.items():
        k = k.removeprefix("FLAGS_")
        if k not in _REGISTRY:
            raise ValueError(f"unknown flag FLAGS_{k}")
        _REGISTRY[k]["value"] = v


def get_flags(flags):
    """paddle.get_flags"""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        k2 = k.removeprefix("FLAGS_")
        if k2 not in _REGISTRY:
            raise ValueError(f"unknown flag {k}")
        out[k] = _REGISTRY[k2]["value"]
    return out


def flag_value(name: str):
    return _REGISTRY[name]["value"]


# ---- TPU-relevant flags (counterparts noted) ------------------------------
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf (ref: FLAGS_check_nan_inf)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: log only")
define_flag("use_bfloat16_matmul", True, "prefer bf16 matmul accumulation on MXU")
define_flag("log_memory_stats", False, "log live buffer stats (ref: FLAGS_log_memory_stats)")
define_flag("benchmark", False, "sync after each op for timing (ref: FLAGS_benchmark)")
define_flag("jit_default_backend", "xla", "compiled-step backend")
define_flag("flash_attention_backend", "auto", "auto|pallas|xla for scaled_dot_product_attention")
define_flag("enable_auto_remat", False, "apply jax.checkpoint policy to compiled blocks")
