"""Runtime flag registry.

reference: paddle/common/flags.h:38-89 (PD_DEFINE_* macros),
paddle/common/flags_native.cc (native parser), surfaced as
paddle.set_flags/get_flags (python/paddle/base/framework.py:132,157).

TPU-native: most of the ~190 reference flags control CUDA allocators,
cuDNN autotune, NCCL — irrelevant under XLA. We keep the registry shape
(env-var override `FLAGS_*`, set/get API) and define the flags that
matter on TPU.
"""

from __future__ import annotations

import os
from typing import Any

_REGISTRY: dict[str, dict] = {}


def define_flag(name: str, default: Any, help_: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = {"value": value, "default": default, "help": help_}
    return value


def set_flags(flags: dict):
    """paddle.set_flags"""
    for k, v in flags.items():
        k = k.removeprefix("FLAGS_")
        if k not in _REGISTRY:
            raise ValueError(f"unknown flag FLAGS_{k}")
        _REGISTRY[k]["value"] = v
        _apply_side_effect(k, v)


def _apply_side_effect(name, value):
    """Flags that configure jax/XLA directly take effect on set."""
    if name == "matmul_precision":
        import jax
        jax.config.update("jax_default_matmul_precision",
                          None if value == "default" else value)
    elif name == "jit_cache_dir" and value:
        import jax
        jax.config.update("jax_compilation_cache_dir", value)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    elif name == "observability":
        from ..observability import disable, enable
        s = str(value).lower()
        if s in ("1", "true", "yes", "on"):
            enable()
        else:
            disable()
    elif name == "fault_injection":
        from ..resilience import faults
        faults.arm_spec(value)   # "" disarms; bad specs raise here


def get_flags(flags):
    """paddle.get_flags"""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        k2 = k.removeprefix("FLAGS_")
        if k2 not in _REGISTRY:
            raise ValueError(f"unknown flag {k}")
        out[k] = _REGISTRY[k2]["value"]
    return out


def flag_value(name: str):
    return _REGISTRY[name]["value"]


# ---- TPU-relevant flags (counterparts noted) ------------------------------
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf (ref: FLAGS_check_nan_inf)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: log only")
define_flag("use_bfloat16_matmul", True, "prefer bf16 matmul accumulation on MXU")
define_flag("log_memory_stats", False, "log live buffer stats (ref: FLAGS_log_memory_stats)")
define_flag("benchmark", False, "sync after each op for timing (ref: FLAGS_benchmark)")
define_flag("jit_default_backend", "xla", "compiled-step backend")
define_flag("flash_attention_backend", "auto", "auto|pallas|xla for scaled_dot_product_attention")
define_flag("enable_auto_remat", False, "apply jax.checkpoint policy to compiled blocks")

# numerics / precision (ref: FLAGS_use_mkldnn-era precision knobs collapse
# into XLA precision config)
define_flag("matmul_precision", "default", "default|high|highest -> jax default_matmul_precision")
define_flag("cudnn_deterministic", False, "ref FLAGS_cudnn_deterministic: on TPU maps to XLA deterministic reductions (informational)")
define_flag("embedding_deterministic", 0, "ref FLAGS_embedding_deterministic; TPU scatters are deterministic (informational)")
define_flag("low_precision_op_list", 0, "ref FLAGS_low_precision_op_list: log AMP casts when >0")
# memory (ref: FLAGS_fraction_of_gpu_memory_to_use family -> XLA_PYTHON_CLIENT_*)
define_flag("fraction_of_gpu_memory_to_use", 0.92, "ref name kept; forwards to XLA_PYTHON_CLIENT_MEM_FRACTION at init")
define_flag("allocator_strategy", "auto_growth", "ref FLAGS_allocator_strategy; XLA BFC always (informational)")
define_flag("gpu_memory_limit_mb", 0, "ref FLAGS_gpu_memory_limit_mb; 0 = no cap")
define_flag("eager_delete_tensor_gb", 0.0, "ref FLAGS_eager_delete_tensor_gb; XLA frees by liveness (informational)")
define_flag("use_pinned_memory", True, "ref FLAGS_use_pinned_memory; jax pins host staging buffers (informational)")
# distributed / collectives
define_flag("dynamic_static_unified_comm", True, "ref FLAGS_dynamic_static_unified_comm; one comm stack here by design")
define_flag("nccl_blocking_wait", False, "ref FLAGS_nccl_blocking_wait; XLA collectives are in-program (informational)")
define_flag("distributed_watchdog_timeout_s", 600, "step-watchdog timeout (ref: comm task watchdog)")
define_flag("mesh_rpc_timeout_s", 30.0, "per-op reply budget for the serving-mesh transport (inference/mesh/transport.py EngineProxy); an expired wait raises typed TransportTimeout — the worker is treated gray (reply still owed), never latched lost. A request deadline_s tightens the budget per call; the pool's op_timeout_s overrides")
define_flag("mesh_worker_accept_timeout_s", 120.0, "how long the parent waits for a spawned mesh worker's transport connection (and the worker for its parent's listener) before typed TransportTimeout; engine_spec accept_timeout_s overrides per pool")
define_flag("stop_check_timeout", 3600, "ref FLAGS_stop_check_timeout: elastic trainer liveness window")
define_flag("retain_grad_for_all_tensor", False, "ref FLAGS_retain_grad_for_all_tensor: keep .grad on non-leaf tensors")
# compiled-step behavior
define_flag("use_stride_kernel", False, "ref FLAGS_use_stride_kernel; XLA has no stride kernels (informational)")
define_flag("jit_cache_dir", "", "persistent XLA compilation cache directory ('' = off)")
define_flag("jit_donate_buffers", True, "donate param/opt buffers in compiled train steps")
# PIR-lite compiler layer (paddle_tpu/pir/; ref: paddle/pir + FLAGS_enable_pir_api)
define_flag("pir", True, "route to_static/serving compilation through the PIR pass pipeline (ref FLAGS_enable_pir_api); off = plain jax.jit")
define_flag("pir_passes", "fold,cse,pattern,fuse,dce,shard_search,shard_prop,overlap", "ordered comma list of PIR passes to run (registered: dce,fold,cse,pattern,fuse,shard_search,shard_prop,overlap); each individually toggleable by omission. The three sharding passes no-op outside a shard_prop.mesh_scope / without input annotations, so the single-chip path is unchanged; fuse runs after pattern (never crosses pt.* boundaries) and before dce (which reaps duplicated layout ops)")
define_flag("pir_verify", "boundary", "structural IR verifier (pir/verifier.py): off | boundary (after capture + after the final pass) | on (after capture + after every pass; tests/tools). A rejection degrades the compile to plain jax.jit, counted pir_fallback_total{stage=verify}")
define_flag("compile_cache_dir", "", "persistent PIR compile-cache directory ('' = off): sha256-verified StableHLO artifacts keyed by canonical IR hash + sharding + flags + jax version")
define_flag("compile_cache_max_bytes", 1 << 28, "PIR compile-cache size cap; least-recently-read artifacts are evicted past it")
define_flag("jit_signature_cache_size", 64, "max compiled input signatures kept per StaticFunction (LRU); shape churn past it shows up in jit_retrace_total")
define_flag("pipeline_schedule", "FThenB", "default pipeline schedule: FThenB|1F1B")
define_flag("prim_all", False, "ref FLAGS_prim_all: decompose big ops before autodiff (jax does this inherently; informational)")
define_flag("cinn_bucket_compile", False, "ref FLAGS_cinn_bucket_compile; XLA owns fusion (informational)")
# profiler / debug
define_flag("observability", False, "runtime observability layer (paddle_tpu.observability): metrics registry + span tracing + SLO telemetry; off = zero-cost no-op fast path")
define_flag("flight_recorder_dir", "", "directory flight-recorder postmortem dumps land in ('' = the tempdir); read from the environment by observability/recorder.py so standalone loads see it too")
define_flag("fault_injection", "", "chaos harness spec (paddle_tpu.resilience.faults): 'site:nth:Exc' / 'site:rand(p)@seed:Exc' entries joined by ';'; '' = disarmed (one global load per site)")
define_flag("enable_host_event_recorder_hook", False, "ref FLAGS_enable_host_event_recorder_hook: record host events in profiler")
define_flag("call_stack_level", 1, "ref FLAGS_call_stack_level: error-message stack detail")
define_flag("api_benchmark", False, "per-op wall-time logging in execute()")
define_flag("max_inplace_grad_add", 0, "ref FLAGS_max_inplace_grad_add (informational; tape adds functionally)")
