"""Core imperative Tensor + autograd engine, TPU-native.

This is the TPU-first replacement for the reference's eager stack:

- ``Tensor`` plays the role of ``paddle::Tensor`` / eager `Tensor`
  (reference: paddle/phi/api/include/tensor.h, paddle/fluid/pybind/eager_method.cc)
  but wraps a ``jax.Array`` so every op lowers through XLA.
- The autograd engine replaces the C++ GradNode graph + ``egr::RunBackward``
  (reference: paddle/fluid/eager/backward.cc:105, grad_node_info.h). Instead of
  hand-written per-op grad nodes generated from backward.yaml, we record one
  ``jax.vjp`` closure per executed op ("Node") and run a reverse topological
  walk keyed on monotonically increasing node ids.
- Kernel dispatch (reference: paddle/phi/core/kernel_factory.h:316) collapses
  into XLA: ops are pure jax functions, the "kernel registry" is jax itself.

Design notes (TPU-first):
- Eager ops execute immediately on-device via jax; under `paddle_tpu.jit.to_static`
  the same Tensors wrap tracers, so one code path serves eager and compiled mode.
- `jax.vjp` at op granularity stores residuals exactly like TensorWrapper saved
  inputs in the reference — but XLA owns the memory (BFC allocator), replacing
  AutoGrowthBestFitAllocator (reference: paddle/phi/core/memory/allocation/).
"""

from __future__ import annotations

import weakref
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dtypes
from . import staging as _staging

__all__ = [
    "Tensor",
    "Parameter",
    "EagerParamBase",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "execute",
    "to_tensor",
    "grad_enabled",
]

# ---------------------------------------------------------------------------
# global autograd mode + trace context
# ---------------------------------------------------------------------------

_GRAD_ENABLED = True

# Active jit.to_static trace context (or None). While tracing, Tensor data may
# be jax tracers; buffer mutations are routed through buffer_update() so the
# compiled function can carry them as explicit outputs (the functional
# equivalent of the reference's in-place running-stat updates).
_TRACE_CTX = None


class TraceContext:
    def __init__(self):
        self.mutations = {}  # id(tensor) -> tensor (latest value in ._data)

    def __enter__(self):
        global _TRACE_CTX
        self._prev = _TRACE_CTX
        _TRACE_CTX = self
        return self

    def __exit__(self, *exc):
        global _TRACE_CTX
        _TRACE_CTX = self._prev
        return False


def in_trace():
    return _TRACE_CTX is not None


def buffer_update(t, arr):
    """Mutate a buffer tensor (e.g. BN running stats) in a trace-safe way."""
    if _TRACE_CTX is not None:
        _TRACE_CTX.mutations[id(t)] = t
    t._data = arr


def is_grad_enabled() -> bool:
    """Mirror of paddle.is_grad_enabled (reference: python/paddle/base/dygraph/base.py)."""
    return _GRAD_ENABLED


def grad_enabled() -> bool:
    return _GRAD_ENABLED


class set_grad_enabled:
    """Context manager / function toggling grad recording."""

    def __init__(self, mode: bool):
        global _GRAD_ENABLED
        self.prev = _GRAD_ENABLED
        _GRAD_ENABLED = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _GRAD_ENABLED
        _GRAD_ENABLED = self.prev
        return False


class _NoGrad:
    """paddle.no_grad: usable as decorator and context manager."""

    def __call__(self, func=None):
        if func is None:
            return self
        import functools

        @functools.wraps(func)
        def wrapper(*a, **k):
            with _NoGrad():
                return func(*a, **k)

        return wrapper

    def __enter__(self):
        global _GRAD_ENABLED
        self._prev = _GRAD_ENABLED
        _GRAD_ENABLED = False
        return self

    def __exit__(self, *exc):
        global _GRAD_ENABLED
        _GRAD_ENABLED = self._prev
        return False


def no_grad(func=None):
    ng = _NoGrad()
    if func is not None:
        return ng(func)
    return ng


class enable_grad(_NoGrad):
    def __enter__(self):
        global _GRAD_ENABLED
        self._prev = _GRAD_ENABLED
        _GRAD_ENABLED = True
        return self


# ---------------------------------------------------------------------------
# autograd graph
# ---------------------------------------------------------------------------

_node_counter = 0


class Node:
    """One recorded op: the analog of a GradNodeBase + its Edges.

    reference: paddle/fluid/eager/grad_node_info.h:197 (GradNodeBase),
    :53 (Edge). Here the "grad kernel" is the jax.vjp closure, which XLA
    has already specialized to the forward's shapes/dtypes.
    """

    __slots__ = (
        "id",
        "name",
        "vjp_fn",
        "fwd_fn",
        "tape_vjp_fn",
        "in_arrays",
        "in_dtypes",
        "inputs",
        "in_nodes",
        "out_refs",
        "out_shapes",
        "out_dtypes",
        "out_treedef",
        "__weakref__",
    )

    def __init__(self, name, vjp_fn, inputs, out_tensors, out_treedef):
        global _node_counter
        _node_counter += 1
        self.id = _node_counter
        self.name = name
        self.vjp_fn = vjp_fn
        # create_graph support: the recorded forward (set by execute) lets
        # the backward walk re-derive a vjp AS TAPE OPS; custom nodes
        # (PyLayer) instead provide tape_vjp_fn running their python
        # backward on live tape tensors (reference: GeneralGrad +
        # double_grad kernels, paddle/fluid/eager/backward.cc:105)
        self.fwd_fn = None
        self.tape_vjp_fn = None
        self.in_arrays = None   # recorded diff input arrays (create_graph)
        self.in_dtypes = None   # post-AMP-cast dtypes fwd_fn was traced at
        self.inputs = inputs  # list[Tensor] — differentiable inputs
        # snapshot producer nodes NOW: in-place rebinds may later repoint a
        # tensor's ._node at a different node (x.add_() aliasing)
        self.in_nodes = [t._node for t in inputs]
        self.out_refs = [weakref.ref(t) for t in out_tensors]
        self.out_shapes = [t._data.shape for t in out_tensors]
        self.out_dtypes = [t._data.dtype for t in out_tensors]
        self.out_treedef = out_treedef


def _collect_topo(root_node):
    """DFS from root, return nodes sorted by id descending (reverse topo).

    Node ids increase monotonically with execution order, so descending id
    order is a valid reverse-topological order — same trick as the in-degree
    queue in egr::RunBackward (reference: paddle/fluid/eager/backward.cc:105)
    but without needing an explicit in-degree map.
    """
    seen = set()
    stack = [root_node]
    order = []
    while stack:
        node = stack.pop()
        if node is None or node.id in seen:
            continue
        seen.add(node.id)
        order.append(node)
        for n in node.in_nodes:
            if n is not None:
                stack.append(n)
    order.sort(key=lambda n: n.id, reverse=True)
    return order


def _run_backward(tensors, grad_tensors=None, retain_graph=False, capture=None,
                  create_graph=False):
    """Reverse-mode walk. reference: paddle/fluid/eager/backward.cc:105.

    If `capture` is a dict {id(tensor): tensor}, accumulated cotangents for
    those tensors are returned in a dict instead of / in addition to being
    deposited into `.grad` (serves paddle.grad / GeneralGrad,
    reference: paddle/fluid/eager/backward.cc GeneralGrad).

    With create_graph=True every cotangent is itself a live tape Tensor and
    each node's backward runs through execute() (re-deriving the vjp from
    the node's recorded forward), so the returned gradients can be
    differentiated again — the reference's double-grad path
    (test/legacy_test/test_imperative_double_grad.py)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # pending cotangents keyed by tensor identity (raw arrays normally;
    # live tape Tensors under create_graph)
    pending: dict[int, Any] = {}
    keep: dict[int, Tensor] = {}

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() on a tensor with stop_gradient=True has no effect"
            )
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs"
                )
            g_arr = jnp.ones_like(t._data)
            if create_graph:
                g_arr = Tensor(g_arr, stop_gradient=True)
        elif create_graph:
            # keep the caller's Tensor intact: its own history must stay
            # differentiable through the second backward
            g_arr = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        _accum(pending, keep, t, g_arr)
        if t._node is not None:
            roots.append(t._node)

    captured = {} if capture is not None else None

    # leaf roots: just deposit grad
    if not roots:
        for t in tensors:
            g = pending.pop(id(t), None)
            if g is not None:
                g = _apply_grad_hooks(t, g)
            if capture is not None and id(t) in capture:
                captured[id(t)] = g
            else:
                _deposit_leaf_grad(t, g)
        return captured

    # leaf grads accumulate here and deposit once at the end, so gradient
    # hooks observe the COMPLETE gradient (a leaf consumed by several ops
    # receives one hook call, not one per contribution)
    leaf_pending: dict[int, Any] = {}
    leaf_keep: dict[int, Tensor] = {}

    nodes = []
    seen = set()
    for r in roots:
        for n in _collect_topo(r):
            if n.id not in seen:
                seen.add(n.id)
                nodes.append(n)
    nodes.sort(key=lambda n: n.id, reverse=True)

    for node in nodes:
        cots = []
        has_any = False
        for ref, shape, dtype in zip(node.out_refs, node.out_shapes, node.out_dtypes):
            t = ref()
            c = None
            if t is not None:
                c = pending.pop(id(t), None)
                keep.pop(id(t), None)
                # cotangent for t is complete here (all consumer nodes have
                # higher ids and were already processed) — hook + capture point
                if c is not None:
                    c = _apply_grad_hooks(t, c)
                if c is not None and capture is not None and id(t) in capture:
                    captured[id(t)] = c
            if c is None:
                c = jnp.zeros(shape, dtype)
                if create_graph:
                    c = Tensor(c, stop_gradient=True)
            else:
                has_any = True
                if c.dtype != dtype:
                    # mixed-precision graphs (AMP): a downstream op may hand
                    # back an fp32 cotangent for a bf16 output; jax.vjp
                    # requires the exact recorded dtype
                    c = c.astype(dtype)
            cots.append(c)
        if not has_any:
            continue
        if create_graph:
            in_cots = _node_backward_recorded(node, cots)
        else:
            cot_tree = jax.tree_util.tree_unflatten(node.out_treedef, cots)
            in_cots = node.vjp_fn(cot_tree)
        _maybe_check_nan(in_cots, node.name + "_grad")
        if not retain_graph:
            node.vjp_fn = None
            node.fwd_fn = None
            node.tape_vjp_fn = None  # PyLayer: free ctx + saved activations
            node.in_arrays = None
        for t, rec_node, c in zip(node.inputs, node.in_nodes, in_cots):
            if rec_node is None:
                _accum(leaf_pending, leaf_keep, t, c)
            else:
                _accum(pending, keep, t, c)

    # anything left pending whose node was unreachable: treat as leaf
    for tid, c in pending.items():
        t = keep.get(tid)
        if t is None and capture is not None:
            t = capture.get(tid)
        if t is not None and (t._node is None or (capture is not None
                                                  and tid in capture)):
            _accum(leaf_pending, leaf_keep, t, c)

    # flush complete leaf gradients: hooks fire once, then capture/deposit
    for tid, c in leaf_pending.items():
        t = leaf_keep[tid]
        c = _apply_grad_hooks(t, c)
        if capture is not None and tid in capture:
            captured[tid] = captured[tid] + c if tid in captured else c
        else:
            _deposit_leaf_grad(t, c)
    return captured


def _accum(pending, keep, t, g):
    tid = id(t)
    if tid in pending:
        pending[tid] = pending[tid] + g
    else:
        pending[tid] = g
        keep[tid] = t


def _node_backward_recorded(node, cot_tensors):
    """One node's backward as RECORDED ops: gradients come out as live tape
    Tensors whose history covers both the node's primal inputs and the
    incoming cotangents, so a second backward differentiates through them.
    reference: the generated double_grad kernels + GeneralGrad
    (paddle/fluid/eager/backward.cc:105)."""
    if node.tape_vjp_fn is not None:  # PyLayer: user backward on live tensors
        return node.tape_vjp_fn(cot_tensors)
    fwd = node.fwd_fn
    if fwd is None:
        raise RuntimeError(
            f"create_graph=True: node '{node.name}' was recorded without a "
            "re-differentiable forward (its graph was already freed by an "
            "earlier backward without retain_graph)")
    k = len(node.inputs)
    for t, rec in zip(node.inputs, node.in_arrays):
        if t._data is not rec:
            # the recompute would evaluate at the MUTATED value and silently
            # disagree with the recorded residuals (torch raises the same way
            # for in-place modification of needed variables)
            raise RuntimeError(
                f"create_graph=True: an input of '{node.name}' was modified "
                "in-place after the forward; its second-order gradient "
                "would be computed at the new value. Clone the tensor "
                "before mutating it.")
    treedef = node.out_treedef
    in_dtypes = node.in_dtypes

    def grad_op(*args):
        primals, cots = args[:k], args[k:]
        # re-apply the recorded (possibly AMP-cast) trace dtypes: fwd_fn
        # was traced over post-cast arrays and the cotangents carry the
        # recorded output dtypes
        primals = tuple(
            p.astype(dt) if p.dtype != dt else p
            for p, dt in zip(primals, in_dtypes))
        _, vjp_fn = jax.vjp(fwd, *primals)
        return tuple(vjp_fn(jax.tree_util.tree_unflatten(treedef, list(cots))))

    try:
        out = execute(grad_op, *node.inputs, *cot_tensors,
                      _name=node.name + "_grad")
    except Exception as e:
        msg = str(e)
        import traceback as _tb
        tb_text = "".join(_tb.format_exception(type(e), e, e.__traceback__))
        if "custom_vjp" in msg or "custom_jvp" in msg \
                or "pallas" in tb_text.lower():
            # the recorded forward contains a kernel whose backward is not
            # itself differentiable (e.g. a raw pallas_call custom_vjp) and
            # no dense _ho_fwd was registered for it
            raise RuntimeError(
                f"create_graph=True through '{node.name}': this op's "
                f"backward is not re-differentiable "
                f"({type(e).__name__}: {msg[:240]}). Re-run the forward on "
                "the op's dense/XLA fallback for higher-order gradients — "
                "for attention, set FLAGS_flash_attention_backend=xla."
            ) from e
        raise
    return out if isinstance(out, (list, tuple)) else (out,)


def _apply_grad_hooks(t, g):
    """Run a tensor's registered gradient hooks over its complete cotangent.
    reference: paddle/fluid/eager/hooks.h (TensorHook::operator())."""
    hooks = t.__dict__.get("_grad_hooks") if hasattr(t, "__dict__") else None
    if not hooks:
        return g
    live = isinstance(g, Tensor)  # create_graph: keep the tape alive
    for hook in list(hooks.values()):
        r = hook(g if live else Tensor(g, stop_gradient=True))
        if r is None:
            continue
        if live:
            g = r if isinstance(r, Tensor) else Tensor(jnp.asarray(r))
        else:
            g = r._data if isinstance(r, Tensor) else jnp.asarray(r)
    return g


def _deposit_leaf_grad(t, g):
    if g is None or t.stop_gradient:
        return
    if isinstance(g, Tensor):  # create_graph walk: .grad stays detached
        g = g._data
    if t._grad is None:
        t._grad = Tensor(g, stop_gradient=True)
    else:
        t._grad = Tensor(t._grad._data + g, stop_gradient=True)


# ---------------------------------------------------------------------------
# op execution + recording
# ---------------------------------------------------------------------------


_STAGING_SCOPE = None  # set by framework.staging.StagingScope (graph breaks)


def _unwrap(x):
    if isinstance(x, Tensor):
        d = x._data
        if isinstance(d, _staging.StagedBox):
            return d.real if d.real is not None else d._materialize()
        return d
    return x


# AMP cast hook installed by paddle_tpu.amp (kept as a function pointer to
# avoid a circular import). Signature: (name, arrays) -> arrays.
_amp_cast_hook = None
_op_observer_hook = None  # amp.debugging operator-stats collection

def _maybe_check_nan(out, name):
    """FLAGS_check_nan_inf: scan op outputs for NaN/Inf when enabled.
    reference: paddle/fluid/eager/nan_inf_utils.h CheckTensorHasNanOrInf —
    there a per-kernel device scan; here one jnp.isfinite reduce per output
    (eager only: traced values are abstract, and jit programs get checked
    at their eager call sites)."""
    from . import flags as _flags
    if not _flags.flag_value("check_nan_inf") or _TRACE_CTX is not None:
        return out
    for leaf in jax.tree_util.tree_leaves(out):
        if (hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.inexact)
                and not bool(jnp.all(jnp.isfinite(leaf)))):
            msg = (f"Operator '{name}' output contains NaN or Inf "
                   f"(FLAGS_check_nan_inf is set)")
            if _flags.flag_value("check_nan_inf_level") == 0:
                raise RuntimeError(msg)
            import warnings
            warnings.warn(msg, RuntimeWarning)
    return out


def execute(f: Callable, *inputs, _name: str = None, _ho_fwd: Callable = None,
            **static_kwargs):
    """Run pure jax function `f(*arrays, **static_kwargs)`, recording a vjp
    Node if any Tensor input requires grad.

    This is the single dispatch point replacing the reference's generated
    `*_ad_func` forward functions (paddle/fluid/eager/auto_code_generator/
    generator/eager_gen.py) — one generic recorder instead of 1600 generated
    C++ grad-node classes, because jax.vjp derives the backward for free.
    AMP auto-cast (reference: paddle/fluid/eager/amp_auto_cast.h) hooks in
    here too, as does the NaN/Inf scanner.
    """
    if _STAGING_SCOPE is not None and _STAGING_SCOPE.active:
        # graph-break staged mode: defer the op into the prefix DAG
        return _STAGING_SCOPE.stage(f, inputs, _name, static_kwargs)

    arrs = [_unwrap(x) for x in inputs]
    if _amp_cast_hook is not None:
        arrs = _amp_cast_hook(_name or getattr(f, "__name__", "op"), arrs)
    if _op_observer_hook is not None:  # amp.debugging op stats: POST-cast
        # dtypes, so the table shows the precision ops actually ran in
        _op_observer_hook(_name or getattr(f, "__name__", "op"), arrs)

    diff_idx = []
    if _GRAD_ENABLED:
        for i, x in enumerate(inputs):
            if isinstance(x, Tensor) and not x.stop_gradient and not jnp.issubdtype(
                x._data.dtype, jnp.integer
            ) and x._data.dtype != jnp.bool_:
                diff_idx.append(i)

    if _TRACE_CTX is not None:
        # Inside a to_static trace: don't record per-op vjp nodes (the whole
        # graph gets one outer vjp); express stop_gradient barriers directly
        # in the traced graph so the outer vjp respects them.
        for i, x in enumerate(inputs):
            if (isinstance(x, Tensor) and x.stop_gradient
                    and jnp.issubdtype(jnp.asarray(arrs[i]).dtype, jnp.inexact)):
                arrs[i] = jax.lax.stop_gradient(arrs[i])
        out = f(*arrs, **static_kwargs)
        return _wrap_outputs(out, stop_gradient=not diff_idx)

    if not diff_idx:
        out = f(*arrs, **static_kwargs)
        _maybe_check_nan(out, _name or getattr(f, "__name__", "op"))
        return _wrap_outputs(out, stop_gradient=True)

    const = list(arrs)

    def _close_over_consts(fn):
        def g(*diff_arrs):
            full = list(const)
            for i, a in zip(diff_idx, diff_arrs):
                full[i] = a
            return fn(*full, **static_kwargs)
        return g

    g = _close_over_consts(f)
    diff_arrs = [arrs[i] for i in diff_idx]
    out, vjp_fn = jax.vjp(g, *diff_arrs)
    _maybe_check_nan(out, _name or getattr(f, "__name__", "op"))

    flat, treedef = jax.tree_util.tree_flatten(out)
    # only record if at least one output is inexact (differentiable)
    if not any(jnp.issubdtype(jnp.asarray(o).dtype, jnp.inexact) for o in flat):
        return _wrap_outputs(out, stop_gradient=True)

    out_tensors = [Tensor(o, stop_gradient=False) for o in flat]
    node = Node(
        _name or getattr(f, "__name__", "op"),
        vjp_fn,
        [inputs[i] for i in diff_idx],
        out_tensors,
        treedef,
    )
    # create_graph: re-derivable vjp over the same consts. An op whose
    # primal path uses a custom_vjp Pallas kernel (not differentiable past
    # first order) may hand a mathematically-equal dense `_ho_fwd`; the
    # recorded forward is then the dense one, so higher-order grads work
    # while the first-order path keeps the fast kernel.
    node.fwd_fn = g if _ho_fwd is None else _close_over_consts(_ho_fwd)
    # pre-cast originals (mutation detection) + post-cast trace dtypes
    node.in_arrays = [inputs[i]._data for i in diff_idx]
    node.in_dtypes = [a.dtype for a in diff_arrs]
    for t in out_tensors:
        t._node = node
    return jax.tree_util.tree_unflatten(treedef, out_tensors)


def _wrap_outputs(out, stop_gradient=True):
    return jax.tree_util.tree_map(
        lambda o: Tensor(o, stop_gradient=stop_gradient), out
    )


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------


class Tensor:
    """Imperative tensor over jax.Array.

    API parity model: paddle.Tensor (reference: paddle/phi/api/include/tensor.h
    + python monkey patches in python/paddle/base/dygraph/tensor_patch_methods.py).
    `stop_gradient` defaults True like paddle; Parameters set it False.
    """

    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "name", "persistable", "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array):
            if dtype is not None:
                data = jnp.asarray(data, dtype=_dtypes.convert_dtype(dtype))
            else:
                data = _dtypes.asarray_default(data)
        elif dtype is not None:
            dt = _dtypes.convert_dtype(dtype)
            if data.dtype != dt:
                data = data.astype(dt)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self.name = name
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        if devs is None:
            return "unknown"
        try:
            return str(next(iter(self._data.devices())))
        except Exception:
            return "unknown"

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from ..tensor import linalg

        return linalg.transpose_last2(self) if self.ndim >= 2 else self

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def astype(self, dtype):
        dt = _dtypes.convert_dtype(dtype)
        return execute(lambda a: a.astype(dt), self, _name="cast")

    cast = astype

    def detach(self):
        data = self._data
        if _TRACE_CTX is not None and jnp.issubdtype(data.dtype, jnp.inexact):
            data = jax.lax.stop_gradient(data)
        return Tensor(data, stop_gradient=True)

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return execute(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else jnp.array(a), self, _name="clone")

    def numel(self):
        return int(self._data.size)

    def element_size(self):
        return self._data.dtype.itemsize

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _run_backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._data))
        else:
            self._grad = None

    def register_hook(self, hook):
        """Call hook(grad) when this tensor's gradient is computed during
        backward; a non-None return value replaces the gradient.
        reference: tensor_patch_methods.py register_hook /
        paddle/fluid/eager/hooks.h TensorHook. Returns a removable handle."""
        if self.stop_gradient:
            raise RuntimeError(
                "register_hook on a tensor with stop_gradient=True is "
                "meaningless (no gradient will ever be computed)")
        hooks = self.__dict__.setdefault("_grad_hooks", {})
        hid = self.__dict__.get("_grad_hook_next", 0)
        self.__dict__["_grad_hook_next"] = hid + 1  # ids never reused, so a
        # stale handle's second remove() can't delete a later hook
        hooks[hid] = hook

        class _HookHandle:
            def remove(_self):
                hooks.pop(hid, None)
                return True

        return _HookHandle()

    # -- in-place helpers ---------------------------------------------------
    def _rebind(self, new: "Tensor"):
        """In-place semantics (x.add_(y)): rebind data + node, keeping this
        Python object. Functional under the hood (no aliasing), which keeps
        autograd sound — the reference needs inplace version counters
        (paddle/fluid/eager/autograd_meta.h) for the same safety."""
        self._data = new._data
        self._node = new._node
        if self._node is not None:
            # repoint the node's weakref output to self so cotangents route here
            for i, ref in enumerate(self._node.out_refs):
                if ref() is new:
                    self._node.out_refs[i] = weakref.ref(self)
        self.stop_gradient = new.stop_gradient and self.stop_gradient
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._data
        else:
            arr = jnp.asarray(value)
        self._data = arr.astype(self._data.dtype).reshape(self._data.shape)
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        return (
            f"Tensor(shape={self.shape}, dtype={_dtypes.dtype_name(self.dtype)}, "
            f"stop_gradient={sg},\n       {np.asarray(self._data)})"
        )

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        idx = _index_unwrap(idx)
        return execute(lambda a: a[idx], self, _name="getitem")

    def __setitem__(self, idx, value):
        idx = _index_unwrap(idx)
        v = value._data if isinstance(value, Tensor) else value
        new = execute(
            lambda a, v=v: a.at[idx].set(v if not isinstance(v, jax.Array) else v.astype(a.dtype)),
            self,
            _name="setitem",
        )
        self._rebind(new)

    def __hash__(self):
        return id(self)

    def dims(self):
        return self.shape

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        # to(dtype) / to(device) / to(device, dtype)
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in _dtypes.NAME2DTYPE:
                out = out.astype(a)
            elif hasattr(a, "dtype") or a in (None,):
                pass
        return out

    def pin_memory(self):
        return self


def _index_unwrap(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_index_unwrap(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


_live_parameters = weakref.WeakValueDictionary()
_param_counter = 0


def live_parameters():
    """All live Parameters in creation order — used by jit.to_static to lift
    closure-captured params into traced inputs."""
    return [p for _, p in sorted(_live_parameters.items())]


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, tracked by Layer.

    reference: python/paddle/base/framework.py EagerParamBase."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        global _param_counter
        super().__init__(data, dtype=dtype, stop_gradient=not trainable)
        self.name = name
        self.persistable = True
        _param_counter += 1
        self._param_uid = _param_counter
        _live_parameters[_param_counter] = self

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


EagerParamBase = Parameter


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py:to_tensor)."""
    if isinstance(data, Tensor) and dtype is None:
        t = Tensor(data._data, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
