"""Multi-adapter (LoRA) serving: the adapter store behind the fused
decode scan's per-lane batched deltas.

One base model, thousands of per-tenant finetunes — the canonical
millions-of-users traffic shape. The reference capability is the LoRA
path of the serving stacks this repo reproduces (per-request adapter
selection over a shared base); the TPU-native design keeps the delta
matmuls INSIDE the single fused dispatch instead of branching per
request, which would shatter batching:

  * ``AdapterStore`` — a closed registry of NAMED LoRA adapter sets.
    Residency is a device-resident stacked weight pool per target
    projection: ``A_q [L, n_slots, H, r]`` / ``B_q [L, n_slots, r, Dq]``
    (and the v-projection pair). Slot 0 is the base model and holds
    zeros forever, so a lane with ``adapter_id == 0`` computes
    ``x @ W + (x @ 0) @ 0`` — the delta is exactly zero and greedy
    streams match the storeless engine token for token.
  * hot-load / evict — ``acquire`` refcounts a named adapter into a
    pool slot (LRU-evicting an idle slot when full) and ``release``
    drops the ref when the request retires. Uploads are plain
    ``pool.at[:, slot].set(w)`` dispatches: jax's async dispatch
    overlaps the copy with in-flight decode tiles, so a cold adapter
    never stalls warm lanes — and because arrays are functional, a tile
    already dispatched keeps reading the buffer it was given.
  * recompile-free swap — the serving engine folds ``program_key``
    (pool SHAPE: n_slots and rank, never contents) into the PIR
    compile-cache key. Loading, evicting, or overwriting adapters
    changes only array *contents*, so the base program never recompiles
    (pinned via ``jit_retrace_total`` delta == 0 across churn).

Degrade contract (house style): the store never half-serves. A failed
``acquire``/residency check at admission is a typed
``AdapterLoadError`` (or an injected transient) and the engine rejects
the request with ``finish_reason='rejected'`` — a wrong-weights stream
is the one outcome that must be impossible. In-flight lanes on other
adapters are untouched; their slots were never written.
"""

from __future__ import annotations

import time

import numpy as np

import jax.numpy as jnp

from ..observability.catalog import metric as _metric
from ..observability.recorder import get_recorder as _get_recorder

__all__ = ["AdapterStore", "AdapterLoadError", "LoraWeights",
           "make_demo_store", "demo_store_for_engine", "per_adapter_slos"]


class AdapterLoadError(RuntimeError):
    """The store could not make a named adapter resident (unknown name,
    every slot pinned by live lanes, or a store fault). Admission treats
    it as a typed rejection — never a silent base-model fallback."""


class LoraWeights:
    """One named adapter set: per-layer A/B factors for the q and v
    projections, host-side until loaded. Shapes (L = layers, H = hidden,
    r = rank, Dq/Dv = projection output widths):

        a_q [L, H, r]   b_q [L, r, Dq]
        a_v [L, H, r]   b_v [L, r, Dv]
    """

    __slots__ = ("name", "a_q", "b_q", "a_v", "b_v")

    def __init__(self, name, a_q, b_q, a_v, b_v):
        self.name = str(name)
        self.a_q = np.asarray(a_q)
        self.b_q = np.asarray(b_q)
        self.a_v = np.asarray(a_v)
        self.b_v = np.asarray(b_v)
        if self.a_q.ndim != 3 or self.b_q.ndim != 3 \
                or self.a_v.ndim != 3 or self.b_v.ndim != 3:
            raise ValueError(f"adapter {name!r}: factors must be "
                             "[L, H, r] / [L, r, D] stacks")
        if self.a_q.shape[-1] != self.b_q.shape[1] \
                or self.a_v.shape[-1] != self.b_v.shape[1]:
            raise ValueError(f"adapter {name!r}: rank mismatch between "
                             "A and B factors")


class AdapterStore:
    """Closed registry of named LoRA adapter sets over a bounded
    device-resident slot pool. See the module docstring for the
    contract; the engine-facing surface is:

        store.acquire(name) -> adapter_id   (refcount++, hot-load)
        store.check_resident(adapter_id)    (gather-side validation)
        store.release(adapter_id)           (refcount--)
        store.can_serve(name)               (router placement check)
        store.program_key                   (shape-only compile key)

    ``n_slots`` INCLUDES the reserved all-zeros base slot 0, so a store
    with n_slots=5 serves at most 4 concurrent distinct adapters.
    """

    def __init__(self, num_layers, hidden, q_out, v_out, rank,
                 n_slots=8, max_adapters=256):
        if n_slots < 2:
            raise ValueError("n_slots must be >= 2 (slot 0 is the base)")
        self.num_layers = int(num_layers)
        self.hidden = int(hidden)
        self.q_out = int(q_out)
        self.v_out = int(v_out)
        self.rank = int(rank)
        self.n_slots = int(n_slots)
        self.max_adapters = int(max_adapters)
        L, S, H, r = self.num_layers, self.n_slots, self.hidden, self.rank
        # the device pools; slot 0 stays all-zeros for the store's life
        self.A_q = jnp.zeros((L, S, H, r), jnp.float32)
        self.B_q = jnp.zeros((L, S, r, self.q_out), jnp.float32)
        self.A_v = jnp.zeros((L, S, H, r), jnp.float32)
        self.B_v = jnp.zeros((L, S, r, self.v_out), jnp.float32)
        self._registry: dict[str, LoraWeights] = {}   # closed name set
        self._slot_of: dict[str, int] = {}            # resident name->slot
        self._name_of: dict[int, str] = {}
        self._refs: dict[int, int] = {}               # slot -> refcount
        self._lru: list[int] = []                     # idle order, old first
        self._loads = 0
        self._evictions = 0
        self._rec = _get_recorder()
        self._m_resident = _metric("serving_adapter_resident")
        self._m_upload = _metric("serving_adapter_upload_seconds")

    @classmethod
    def for_model(cls, model, rank=4, n_slots=8, max_adapters=256):
        """Dimension a store from a LlamaForCausalLM-style config: the
        q delta lands on [H, nh*hd] and the v delta on [H, nkv*hd]."""
        cfg = model.config
        hd = cfg.hidden_size // cfg.num_attention_heads
        return cls(cfg.num_hidden_layers, cfg.hidden_size,
                   cfg.num_attention_heads * hd,
                   cfg.num_key_value_heads * hd,
                   rank, n_slots=n_slots, max_adapters=max_adapters)

    # --- registry ---------------------------------------------------------
    def register(self, name, a_q, b_q, a_v, b_v):
        """Add a named adapter to the closed registry (host weights;
        residency comes later via acquire). Shape-checked against the
        store's dimensions so a bad adapter fails HERE, not as a shape
        error inside the fused scan."""
        name = str(name)
        if not name or name == "base":
            raise ValueError("adapter name must be non-empty and not "
                             "'base' (the reserved slot-0 identity)")
        if name not in self._registry \
                and len(self._registry) >= self.max_adapters:
            raise AdapterLoadError(
                f"adapter registry full ({self.max_adapters}); the id "
                "space is bounded by construction")
        w = LoraWeights(name, a_q, b_q, a_v, b_v)
        want = {
            "a_q": (self.num_layers, self.hidden, self.rank),
            "b_q": (self.num_layers, self.rank, self.q_out),
            "a_v": (self.num_layers, self.hidden, self.rank),
            "b_v": (self.num_layers, self.rank, self.v_out),
        }
        for attr, shape in want.items():
            got = getattr(w, attr).shape
            if tuple(got) != shape:
                raise ValueError(
                    f"adapter {name!r}: {attr} shape {tuple(got)} != "
                    f"store shape {shape}")
        self._registry[name] = w
        return name

    def names(self):
        return sorted(self._registry)

    def can_serve(self, name):
        """Placement check (mesh router): True when the name is in the
        closed registry — resident now or hot-loadable on demand."""
        return str(name) in self._registry

    # --- residency --------------------------------------------------------
    @property
    def program_key(self):
        """What the compiled programs depend on: pool SHAPE only. Every
        load/evict/overwrite leaves this key — and therefore the PIR
        compile-cache entry — untouched."""
        return ("lora", self.n_slots, self.rank)

    def resident(self):
        return dict(self._slot_of)

    def refcount(self, adapter_id):
        return self._refs.get(int(adapter_id), 0)

    def acquire(self, name):
        """Refcount the named adapter resident and return its slot id.
        A cold adapter hot-loads into a free (or LRU idle) slot; the
        upload is an async device dispatch overlapped with whatever is
        in flight. Raises AdapterLoadError when the name is unknown or
        every non-base slot is pinned by live lanes."""
        name = str(name)
        if name not in self._registry:
            raise AdapterLoadError(
                f"unknown adapter {name!r}; registered: {self.names()}")
        slot = self._slot_of.get(name)
        if slot is not None:
            self._refs[slot] = self._refs.get(slot, 0) + 1
            if slot in self._lru:
                self._lru.remove(slot)
            return slot
        slot = self._free_slot()
        if slot is None:
            raise AdapterLoadError(
                f"no adapter slot free for {name!r}: all "
                f"{self.n_slots - 1} slots pinned by live lanes")
        self._upload(slot, self._registry[name])
        self._slot_of[name] = slot
        self._name_of[slot] = name
        self._refs[slot] = 1
        self._loads += 1
        _metric("serving_adapter_loads_total", adapter=name).inc()
        self._m_resident.set(len(self._slot_of))
        if self._rec.enabled:
            self._rec.record("adapter", action="load", adapter=name,
                             slot=slot)
        return slot

    def check_resident(self, adapter_id):
        """Gather-side validation at lane bind time: the slot the lane
        will gather from must still belong to a live adapter. Raises
        AdapterLoadError otherwise (the engine rejects typed — never a
        wrong-weights gather)."""
        aid = int(adapter_id)
        if aid == 0:
            return
        if aid not in self._name_of or self._refs.get(aid, 0) <= 0:
            raise AdapterLoadError(
                f"adapter slot {aid} is not resident (evicted or never "
                "loaded); refusing to gather stale weights")

    def release(self, adapter_id):
        """Drop one reference. A slot at refcount 0 stays resident (warm
        for the next acquire) but becomes LRU-evictable."""
        slot = int(adapter_id)
        if slot == 0 or slot not in self._refs:
            return
        self._refs[slot] = max(0, self._refs[slot] - 1)
        if self._refs[slot] == 0 and slot not in self._lru:
            self._lru.append(slot)

    def _free_slot(self):
        used = set(self._name_of)
        for s in range(1, self.n_slots):
            if s not in used:
                return s
        while self._lru:
            victim = self._lru.pop(0)
            if self._refs.get(victim, 0) > 0:
                continue        # re-acquired since it went idle
            name = self._name_of.pop(victim)
            self._slot_of.pop(name, None)
            self._refs.pop(victim, None)
            self._evictions += 1
            _metric("serving_adapter_evictions_total", adapter=name).inc()
            self._m_resident.set(len(self._slot_of))
            if self._rec.enabled:
                self._rec.record("adapter", action="evict", adapter=name,
                                 slot=victim)
            # no zeroing needed: the incoming upload overwrites the slot
            # and no live lane can reference it (refcount was 0)
            return victim
        return None

    def _upload(self, slot, w):
        t0 = time.perf_counter()
        self.A_q = self.A_q.at[:, slot].set(
            jnp.asarray(w.a_q, jnp.float32))
        self.B_q = self.B_q.at[:, slot].set(
            jnp.asarray(w.b_q, jnp.float32))
        self.A_v = self.A_v.at[:, slot].set(
            jnp.asarray(w.a_v, jnp.float32))
        self.B_v = self.B_v.at[:, slot].set(
            jnp.asarray(w.b_v, jnp.float32))
        # host-side dispatch wall only: the copy itself overlaps decode
        # (async dispatch); nothing here blocks on the device
        self._m_upload.observe(time.perf_counter() - t0)

    def stats(self):
        return {"loads": self._loads, "evictions": self._evictions,
                "resident": len(self._slot_of),
                "registered": len(self._registry)}


def _register_demo(store, names, seed, scale):
    L, H, r = store.num_layers, store.hidden, store.rank
    for i, name in enumerate(names):
        rs = np.random.RandomState(seed * 10_007 + i)
        store.register(
            name,
            rs.randn(L, H, r).astype(np.float32) * scale,
            rs.randn(L, r, store.q_out).astype(np.float32) * scale,
            rs.randn(L, H, r).astype(np.float32) * scale,
            rs.randn(L, r, store.v_out).astype(np.float32) * scale)
    return store


def make_demo_store(model, names, rank=4, n_slots=8, seed=0, scale=0.5):
    """A store populated with small random adapters — the loadgen /
    bench / chaos-drill fixture. Deterministic in `seed`; `scale` keeps
    the deltas small enough that decode stays numerically tame while
    still flipping greedy argmaxes vs the base model (delta std per
    projection element is about 2·scale²·|x|, so the 0.5 default
    perturbs logits by a few percent on the tiny test configs)."""
    store = AdapterStore.for_model(model, rank=rank, n_slots=n_slots)
    return _register_demo(store, names, seed, scale)


def demo_store_for_engine(engine, names, rank=4, n_slots=8, seed=0,
                          scale=0.5):
    """make_demo_store for callers that only hold a built engine (the
    loadgen auto-install path): dimensions the store from the engine's
    own stacked params instead of a model config. Same seed + same
    dimensions produce byte-identical weights to make_demo_store."""
    num_layers = int(next(iter(engine.stacked.values())).shape[0])
    hidden = int(engine.embed_w.shape[1])
    cfg = engine.cfg
    store = AdapterStore(num_layers, hidden,
                         int(cfg["heads"] * cfg["head_dim"]),
                         int(cfg["kv_heads"] * cfg["head_dim"]),
                         rank, n_slots=n_slots)
    return _register_demo(store, names, seed, scale)


def per_adapter_slos(names, ttft_objective=2.5, tpot_objective=0.25):
    """Per-adapter SLOSpecs over the adapter-labeled serving histograms
    — the existing SLOEngine evaluates them like any other spec (the
    `labels=` filter keeps each verdict scoped to one adapter)."""
    from ..observability.slo import SLOSpec
    specs = []
    for n in names:
        specs.append(SLOSpec(
            f"adapter_{n}_ttft_p95", "quantile",
            "serving_adapter_ttft_seconds", objective=ttft_objective,
            q=0.95, labels={"adapter": str(n)}))
        specs.append(SLOSpec(
            f"adapter_{n}_tpot_p99", "quantile",
            "serving_adapter_tpot_seconds", objective=tpot_objective,
            q=0.99, labels={"adapter": str(n)}))
    return specs
