"""Per-scenario speculative drafters for the serving engine.

The engine's built-in drafter (serving._ngram_draft) is one flat
prompt-lookup: a single n-gram length for every workload, which is why
the PERF.md decode A/B sits at ~0.49 acceptance — chat-style short
contexts rarely match a long n-gram and fall through to the
repeat-step-token fallback, while long-document contexts could support
a stricter (higher-precision) match than the flat default attempts.

This module feeds the loadgen *scenario label* into the engine's
pluggable ``drafter=`` hook: each scenario maps to an ordered n-gram
BACKOFF ladder (longest/most-precise first; lanes that fail a longer
lookup retry the shorter one before the repeat-token fallback). The
drafter stays pure jnp over the device-resident history buffer, so it
traces into the fused decode scan exactly like the built-in one, and
the committed stream is still byte-identical to non-speculative decode
— acceptance only changes how many drafts survive verification.

Two harnesses measure this, and they sit in very different regimes:

* bench.py decode A/B (repetitive tiled-motif prompts, 193 new
  tokens): the flat drafter sits at ~0.48-0.49 acceptance; the tuned
  (3,2)-ladder at depth 2 reaches ~0.58, because the second rung
  converts fallback drafts (almost never accepted) into short-context
  matches and the shallower depth stops betting tokens past where the
  match decays. PERF.md records the current numbers.
* tools/loadgen.py --speculative (Weyl-sequence prompts, 4-12 token
  replies): absolute acceptance is intrinsically tiny (a chaotic tiny
  model emitting a handful of tokens gives prompt-lookup almost
  nothing to match), but the tuned rows still beat the flat drafter
  at equal depth and the report's per-scenario acceptance block makes
  the regime visible instead of hiding it in an aggregate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SCENARIO_DRAFT_STATS", "backoff_drafter", "suffix_drafter",
           "scenario_drafter", "scenario_draft_depth"]

# scenario label -> n-gram statistics for the drafter. "ngrams" is the
# backoff ladder (tried longest-first per lane); "depth" the draft depth
# the harness configures the engine with. The entries are measured, not
# guessed — retune with tools/loadgen.py --speculative after touching
# the drafter or the harness model (PERF.md "auto-sharding + drafting"
# section records the current numbers).
SCENARIO_DRAFT_STATS = {
    "chat": {"ngrams": (3, 2), "depth": 2},
    "long_document": {"ngrams": (2,), "depth": 2},
    "offline_batch": {"ngrams": (3, 2), "depth": 2},
    "structured_output": {"ngrams": (2,), "depth": 2},
    # round 18: tenant-common system prompts give every lane a long
    # shared context — the suffix drafter (longest-match, not a fixed
    # ladder) exploits it; "suffix" selects it over the ngram ladder.
    # (3, 2) is measured, like every row here: deeper match caps LOSE
    # acceptance on the harness model (see suffix_drafter's docstring)
    "shared_prefix": {"suffix": (3, 2), "depth": 2},
}

# scenarios without a tuned row fall back to this ladder (strictly more
# capable than the engine's flat default: same primary, plus a rung,
# and a depth that stops betting past what short replies can accept)
_DEFAULT_STATS = {"ngrams": (3, 2), "depth": 2}


def _lookup(hist, lens, toks, depth, ngram):
    """One prompt-lookup rung: propose the `depth` tokens that followed
    the most recent earlier occurrence of the trailing `ngram`-token
    suffix; also return the per-lane matched mask so a backoff ladder
    can fall through. Mirrors serving._ngram_draft (including the
    cand + depth < n guard that keeps the continuation out of the
    previous step's rejected-draft leftovers)."""
    hmax = hist.shape[1]
    cand = jnp.arange(hmax)

    def one(h, n, t):
        ok = (cand >= ngram - 1) & (cand + depth < n)
        for gback in range(ngram):
            ok &= (h[jnp.clip(cand - gback, 0, hmax - 1)]
                   == h[jnp.clip(n - gback, 0, hmax - 1)])
        j = jnp.max(jnp.where(ok, cand, -1))
        cont = h[jnp.clip(j + 1 + jnp.arange(depth), 0, hmax - 1)]
        return jnp.where(j >= 0, cont, jnp.full((depth,), t)), j >= 0

    drafts, matched = jax.vmap(one)(hist, lens, toks)
    return drafts.astype(jnp.int32), matched


def backoff_drafter(ngrams):
    """Build a ``fn(hist, lens, toks, depth) -> [B, depth] int32``
    drafter that tries each n-gram length in order and keeps, per lane,
    the first rung that matched (unmatched lanes end at the repeat-
    step-token fallback the last rung produces)."""
    ladder = tuple(int(n) for n in ngrams)
    if not ladder or any(n < 1 for n in ladder):
        raise ValueError(f"n-gram ladder must be ints >= 1, got {ngrams!r}")

    def drafter(hist, lens, toks, depth):
        out = have = None
        for n in ladder:
            drafts, matched = _lookup(hist, lens, toks, depth, n)
            if out is None:
                out, have = drafts, matched
            else:
                out = jnp.where(have[:, None], out, drafts)
                have = have | matched
        return out

    drafter.label = "backoff:" + ",".join(str(n) for n in ladder)
    return drafter


def suffix_drafter(max_suffix=3, min_match=2):
    """Round 18: a suffix-automaton-style lookup drafter. Instead of a
    fixed n-gram ladder, each lane finds the earlier position whose
    context shares the LONGEST suffix (up to `max_suffix` tokens, at
    least `min_match`) with the current one and proposes the tokens
    that followed it — the device-parallel equivalent of walking a
    suffix automaton of (prompt + committed history) to its deepest
    state. The min_match floor keeps the short-context precision of
    the ladder's last rung; ties prefer the most recent occurrence.
    The max_suffix default is MEASURED on the bench decode A/B, not
    assumed: on the harness model, deeper caps monotonically lose
    acceptance (8 -> 0.548, 5 -> 0.572, 3 -> 0.597 at depth 2) because
    a chaotic small-vocab stream makes long coincidental matches
    outrank the recent short match that actually predicts — retune
    after touching the harness model. Same pure-jnp contract as
    backoff_drafter: traces into the fused scan, committed streams stay
    byte-identical, only acceptance moves."""
    M = int(max_suffix)
    lo = int(min_match)
    if not (1 <= lo <= M):
        raise ValueError(
            f"need 1 <= min_match <= max_suffix, got ({max_suffix!r}, "
            f"{min_match!r})")

    def drafter(hist, lens, toks, depth):
        hmax = hist.shape[1]
        cand = jnp.arange(hmax)

        def one(h, n, t):
            # h[n] is the step token (scattered by the caller). The
            # cand + depth < n guard keeps the continuation strictly in
            # the PAST (same reason as serving._ngram_draft: positions
            # >= n hold the previous step's rejected-draft leftovers).
            ok = cand + depth < n
            run = ok
            length = jnp.zeros(hmax, jnp.int32)
            for gback in range(M):
                run = (run & (cand - gback >= 0)
                       & (h[jnp.clip(cand - gback, 0, hmax - 1)]
                          == h[jnp.clip(n - gback, 0, hmax - 1)]))
                length = length + run.astype(jnp.int32)
            valid = ok & (length >= lo)
            # maximize (match length, recency): length majorizes, the
            # candidate index breaks ties toward the latest occurrence
            score = jnp.where(valid, length * hmax + cand, -1)
            j = jnp.argmax(score)
            cont = h[jnp.clip(j + 1 + jnp.arange(depth), 0, hmax - 1)]
            return jnp.where(score[j] >= 0, cont, jnp.full((depth,), t))

        return jax.vmap(one)(hist, lens, toks).astype(jnp.int32)

    drafter.label = f"suffix:{M},{lo}"
    return drafter


def scenario_drafter(scenario):
    """The per-scenario drafter for a loadgen scenario label (accepts a
    Scenario object or its name; unknown labels get the default
    ladder). The returned callable carries a ``label`` attribute the
    loadgen report surfaces next to the measured acceptance."""
    name = getattr(scenario, "name", scenario)
    stats = SCENARIO_DRAFT_STATS.get(str(name), _DEFAULT_STATS)
    if "suffix" in stats:
        fn = suffix_drafter(*stats["suffix"])
    else:
        fn = backoff_drafter(stats["ngrams"])
    fn.label = f"scenario:{name}:" + fn.label
    return fn


def scenario_draft_depth(scenario) -> int:
    """The tuned draft depth for a scenario label."""
    name = getattr(scenario, "name", scenario)
    return int(SCENARIO_DRAFT_STATS.get(str(name), _DEFAULT_STATS)["depth"])
