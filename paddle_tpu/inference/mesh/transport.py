"""Process-native worker transport for the serving mesh.

Round 20 makes the mesh's workers real processes. A `ProcessReplica`
fronts a full ContinuousBatchingEngine that lives EITHER in this
process behind an in-memory loopback (deterministic, the tier-1 shape)
OR in a child process reached over a native TCP socket (the
`tests/two_proc_worker.py` launch idiom; worker.py is the child's
main). Both speak the same versioned length-prefixed frame protocol,
and the PR 13 `pack_record` wire format IS the KV payload — a paged-KV
handoff crosses the transport as exactly the bytes `hand_off` already
round-trips, so byte-exact streams carry over unchanged.

Frame (version 1): `<4s magic><u32 header-len><u32 payload-len>` then a
sorted-key JSON header `{"v", "kind", "meta"}` and raw payload bytes.
Deterministic — the same call packs to the same frame.

Failure contract (`mesh.transport_send` fault site): the site arms
BEFORE a frame leaves the client, so a retried send can never
double-dispatch a non-idempotent op. Transient failures retry under the
client's RetryPolicy; exhaustion surfaces `TransportError` — a
ConnectionError subclass, so every existing _TRANSIENT classifier
(handoff retry-then-re-prefill, router failover) absorbs it without new
plumbing. A worker whose transport dies mid-session is treated exactly
like a killed process: the proxy latches lost, the pool tombstones its
lease, and the router re-prefills its uncommitted streams on survivors.

The router/commit/failover semantics stay transport-agnostic: the
`EngineProxy` mirrors the engine duck-type the MeshRouter already
drives (add_request / adopt_identity / step / finished / import_kv /
predicted_*), and greedy streams are pinned byte-identical to the
in-process pool across both transports.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import time
from collections import deque

import numpy as np

from ...distributed.fleet.elastic import ElasticManager
from ...framework.flags import flag_value
from ...observability.catalog import metric as _metric
from ...resilience.faults import FaultInjected, check, fault_point
from ...resilience.retry import RetryPolicy
from ..serving import BackpressureError
from .handoff import pack_record, unpack_record
from .replica import Replica, ReplicaPool

__all__ = ["TRANSPORT_VERSION", "TransportError", "TransportTimeout",
           "TransportFuture",
           "pack_frame", "unpack_frame", "send_frame", "recv_frame",
           "serve_request", "LoopbackClient", "SocketClient",
           "EngineProxy", "ProcessReplica", "ProcessReplicaPool"]

_TRANSIENT = (TimeoutError, ConnectionError, OSError, FaultInjected)

TRANSPORT_VERSION = 1
_MAGIC = b"PTMW"        # paddle_tpu mesh worker

# network-chaos windows (round 21): how long a held reply stays hostage
# when the mesh.net_delay / mesh.net_stall sites are armed. The stall is
# deliberately SHORTER than the health detector's dead_elapsed_s default
# (2.0s) so a drill proves SLOW trips before DEAD.
_NET_DELAY_S = 0.05
_NET_STALL_S = 0.75
_DRAIN_SLICE_S = 0.02   # select granularity of a blocking drain


class TransportError(ConnectionError):
    """A framed round trip that could not be completed (send failed past
    the retry budget, the peer died, or a malformed/wrong-version frame
    arrived). Subclasses ConnectionError ON PURPOSE: every _TRANSIENT
    classifier in the mesh (handoff re-prefill, router failover) already
    knows how to recover from one."""


class TransportTimeout(TransportError):
    """A reply that did not land within its op budget (round 21). Still
    a TransportError — every transient classifier absorbs it — but the
    MEANING differs: the worker is gray (slow, owed a reply that stays
    pending), not dead, so callers must NOT latch the proxy lost on it.
    The health detector, not the timeout, decides when gray becomes
    dead."""


# --- frames ----------------------------------------------------------------

def pack_frame(kind, meta=None, payload=b""):
    """Serialize one protocol frame. `meta` is JSON-safe scalars only;
    bulk bytes ride in `payload` untouched."""
    head = json.dumps({"v": TRANSPORT_VERSION, "kind": str(kind),
                       "meta": meta or {}}, sort_keys=True).encode()
    return (struct.pack("<4sII", _MAGIC, len(head), len(payload))
            + head + payload)


def unpack_frame(buf):
    """Inverse of pack_frame -> (kind, meta, payload). Raises
    TransportError on bad magic, a truncated buffer, or a version this
    build does not speak (versioned so a mixed-version fleet fails
    typed, not with a JSON parse error mid-stream)."""
    if len(buf) < 12:
        raise TransportError(f"truncated frame ({len(buf)} bytes)")
    magic, hlen, plen = struct.unpack_from("<4sII", buf, 0)
    if magic != _MAGIC:
        raise TransportError(f"bad frame magic {magic!r}")
    if len(buf) != 12 + hlen + plen:
        raise TransportError(
            f"frame length mismatch ({len(buf)} != {12 + hlen + plen})")
    head = json.loads(buf[12:12 + hlen].decode())
    if head.get("v") != TRANSPORT_VERSION:
        raise TransportError(
            f"unknown transport version {head.get('v')!r} "
            f"(this build speaks {TRANSPORT_VERSION})")
    return head["kind"], head.get("meta") or {}, buf[12 + hlen:]


def send_frame(sock, kind, meta=None, payload=b""):
    sock.sendall(pack_frame(kind, meta, payload))


def _recv_exact(sock, n, deadline=None):
    """Read exactly n bytes. `deadline` is an absolute perf_counter
    time; past it the read raises typed TransportTimeout (a half-open
    peer can no longer hang the caller forever — the round-20 drain
    blocked here with no way out)."""
    out = bytearray()
    while len(out) < n:
        if deadline is not None:
            rem = deadline - time.perf_counter()
            if rem <= 0.0:
                raise TransportTimeout(
                    f"frame receive expired mid-frame "
                    f"({len(out)}/{n} bytes)")
            sock.settimeout(rem)
        try:
            chunk = sock.recv(n - len(out))
        except socket.timeout:
            raise TransportTimeout(
                f"frame receive expired mid-frame "
                f"({len(out)}/{n} bytes)") from None
        if not chunk:
            raise TransportError("peer closed mid-frame")
        out.extend(chunk)
    return bytes(out)


def recv_frame(sock, timeout=None):
    """Receive one frame; with `timeout` the WHOLE frame (prefix +
    header + payload) must land within that many seconds or typed
    TransportTimeout raises. Default stays blocking (the worker's serve
    loop legitimately waits forever for its parent)."""
    deadline = (None if timeout is None
                else time.perf_counter() + float(timeout))
    try:
        prefix = _recv_exact(sock, 12, deadline)
        magic, hlen, plen = struct.unpack("<4sII", prefix)
        if magic != _MAGIC:
            raise TransportError(f"bad frame magic {magic!r}")
        return unpack_frame(prefix + _recv_exact(sock, hlen + plen,
                                                 deadline))
    finally:
        if timeout is not None:
            try:
                sock.settimeout(None)
            except OSError:
                pass


# --- server-side dispatch ---------------------------------------------------
# One pure function shared by the in-process loopback and the child
# process's socket loop (worker.py), so both transports exercise the
# SAME op surface and marshalling.

# error bases a client can rehydrate typed; everything else surfaces as
# TransportError on the caller side. TimeoutError first: it subclasses
# OSError/ConnectionError in spirit but none of the bases below, and a
# worker-side deadline rejection must come back typed, not RuntimeError.
_ERROR_BASES = (("TimeoutError", TimeoutError),
                ("BackpressureError", BackpressureError),
                ("MemoryError", MemoryError),
                ("ValueError", ValueError),
                ("KeyError", KeyError))


def _marshal_error(e):
    base = next((name for name, cls in _ERROR_BASES
                 if isinstance(e, cls)), "RuntimeError")
    return "error", {"etype": type(e).__name__, "base": base,
                     "msg": str(e)}, b""


def _rehydrate(meta):
    base = meta.get("base")
    msg = f"{meta.get('etype')}: {meta.get('msg')}"
    if base == "TimeoutError":
        # a worker-side deadline rejection lands client-side as the
        # transport's own timeout type, so one except-clause covers
        # "reply too late" and "work refused as already expired"
        return TransportTimeout(msg)
    cls = dict(_ERROR_BASES).get(base)
    return cls(msg) if cls is not None else TransportError(msg)


def _finished_dict(req):
    return {"rid": req.rid, "generated": list(req.generated),
            "finish_reason": req.finish_reason, "tenant": req.tenant,
            "priority": req.priority, "trace_id": req.trace_id,
            "t_arrival": float(req.t_arrival),
            "t_first": None if req.t_first is None else float(req.t_first),
            "deadline_s": req.deadline_s,
            "shed_count": int(getattr(req, "shed_count", 0))}


def serve_request(engine, kind, meta, payload, exports=None):
    """Dispatch one decoded frame against `engine`; returns the reply
    frame parts (kind, meta, payload). `exports` is the worker-held
    list its prefill_sink appends to — drained into every step reply so
    handoff records reach the router without a side channel. Exceptions
    marshal as an error frame (never a torn reply).

    `meta["deadline"]` (round 21) is the REMAINING seconds of the op's
    client-side budget at send time, popped before dispatch. Work that
    arrives already expired is rejected typed (TimeoutError base —
    rehydrates as TransportTimeout) instead of admitted: the engine
    would only expire it later with the blocks already spent."""
    meta = dict(meta or {})
    deadline = meta.pop("deadline", None)
    try:
        if (deadline is not None and float(deadline) <= 0.0
                and kind in ("add_request", "import_kv")):
            _metric("mesh_rpc_timeouts_total", op=kind).inc()
            raise TimeoutError(
                f"{kind} rejected: deadline expired before dispatch")
        if kind == "ping":
            return "ok", {"pid": os.getpid(),
                          "vocab": int(engine.embed_w.shape[0]),
                          "block_size": int(engine.pool.block_size)}, b""
        if kind == "add_request":
            prompt = np.frombuffer(payload, np.int32)
            rid = engine.add_request(prompt, **meta)
            return "ok", {"rid": int(rid)}, b""
        if kind == "cancel":
            ok = bool(engine.cancel(int(meta["rid"])))
            return "ok", {"cancelled": ok}, b""
        if kind == "adopt":
            ok = engine.adopt_identity(meta["rid"], meta["trace_id"],
                                       meta.get("t_arrival"))
            return "ok", {"adopted": bool(ok)}, b""
        if kind == "import_kv":
            rid = engine.import_kv(unpack_record(payload))
            return "ok", {"rid": int(rid)}, b""
        if kind == "step":
            dt = 0.0
            if engine.has_work():
                t0 = time.perf_counter()
                engine.step()
                dt = time.perf_counter() - t0
            fins = [_finished_dict(r) for r in engine.finished.values()]
            engine.finished.clear()
            wires = []
            if exports:
                wires = [pack_record(rec) for rec in exports]
                del exports[:]
            sched = getattr(engine, "scheduler", None)
            out = {"dt": dt,
                   "queue": [[r.tenant, r.priority] for r in engine.queue],
                   "lanes": [None if r is None else r.tenant
                             for r in engine.lanes],
                   "preempted": [[int(rid), req.tenant] for rid, (req, _l, _t)
                                 in engine._preempted.items()],
                   "has_work": bool(engine.has_work()),
                   "svc": engine.predicted_service_seconds(),
                   "brownout_level": (0 if sched is None
                                      else int(getattr(sched, "level", 0))),
                   "finished": fins,
                   "export_sizes": [len(w) for w in wires]}
            blob = b"".join(struct.pack("<I", len(w)) + w for w in wires)
            return "ok", out, blob
        if kind == "snapshot":
            costs = {key: {k: None if v is None else float(v)
                           for k, v in c.items()}
                     for key, c in engine.predicted_costs().items()}
            return "ok", {"costs": costs}, b""
        if kind == "shutdown":
            return "ok", {"bye": True}, b""
        raise ValueError(f"unknown transport op {kind!r}")
    except Exception as e:  # noqa: BLE001 — marshalled, never torn
        return _marshal_error(e)


# --- client futures ---------------------------------------------------------

class TransportFuture:
    """Delivery-complete handle for one asynchronous round trip. done()
    is a non-blocking poll; result() forces completion (draining the
    socket for real workers, counting down the simulated latency for
    loopback). Exceptions re-raise from result().

    result(timeout=...) bounds the wait: past the budget it raises typed
    TransportTimeout and counts `mesh_rpc_timeouts_total{op}` — the
    future stays pending (the reply is still owed; a later drain settles
    it), which is exactly the gray-failure shape: slow, not dead."""

    __slots__ = ("_client", "_resolved", "_value", "_exc", "_polls_left",
                 "_kind", "_ready_at")

    def __init__(self, client=None, polls=0, kind=None):
        self._client = client
        self._resolved = False
        self._value = None
        self._exc = None
        self._polls_left = int(polls)
        self._kind = kind
        # wall-clock hold (mesh.net_delay / mesh.net_stall on loopback):
        # the reply exists but has not "landed" before this time
        self._ready_at = None

    def _complete(self, value):
        self._resolved = True
        self._value = value

    def _fail(self, exc):
        self._resolved = True
        self._exc = exc

    def done(self):
        if not self._resolved and self._client is not None:
            self._client._drain(block=False)
        if not self._resolved:
            return False
        if self._ready_at is not None:
            if time.perf_counter() < self._ready_at:
                return False
            self._ready_at = None
        if self._polls_left > 0:
            # loopback latency model: the copy "lands" only after this
            # many polls — the deterministic stand-in for a NIC transfer
            # overlapping the decode pump
            self._polls_left -= 1
            return False
        return True

    def _timed_out(self, timeout):
        op = self._kind or "unknown"
        _metric("mesh_rpc_timeouts_total", op=op).inc()
        raise TransportTimeout(
            f"reply for {op!r} still owed past the "
            f"{timeout}s op budget (gray, not dead)")

    def result(self, timeout=None):
        deadline = (None if timeout is None
                    else time.perf_counter() + float(timeout))
        while not self._resolved:
            if self._client is None:
                raise TransportError("future abandoned with no client")
            self._client._drain(block=True, deadline=deadline)
        while self._ready_at is not None:
            now = time.perf_counter()
            if now >= self._ready_at:
                self._ready_at = None
                break
            if deadline is not None and now >= deadline:
                self._timed_out(timeout)
            time.sleep(min(0.0005, self._ready_at - now))
        self._polls_left = 0
        if self._exc is not None:
            raise self._exc
        return self._value


class _ClientBase:
    """Shared send discipline: every round trip passes the
    `mesh.transport_send` fault site INSIDE the retried closure and
    BEFORE dispatch, is counted per frame kind, and rehydrates error
    frames typed."""

    def __init__(self, retry=None):
        self._retry = retry

    def _guarded_send(self, kind, send):
        def _attempt():
            fault_point("mesh.transport_send", kind=kind)
            return send()
        try:
            if self._retry is not None:
                out = self._retry.call(_attempt, op="mesh.transport_send")
            else:
                out = _attempt()
        except _TRANSIENT as e:
            err = TransportError(f"transport send failed for {kind!r}: "
                                 f"{e!r}")
            err.__cause__ = e
            raise err
        _metric("mesh_transport_frames_total", kind=kind).inc()
        return out

    @staticmethod
    def _settle(fut, reply):
        kind, meta, payload = reply
        if kind == "error":
            fut._fail(_rehydrate(meta))
        else:
            fut._complete((meta, payload))

    def call(self, kind, meta=None, payload=b"", timeout=None):
        """Synchronous round trip -> (meta, payload). `timeout` bounds
        the reply wait (typed TransportTimeout past it)."""
        return self.call_async(kind, meta, payload).result(timeout=timeout)

    def _drain(self, block, deadline=None):
        raise NotImplementedError

    def close(self):
        pass


class LoopbackClient(_ClientBase):
    """In-process transport: frames still pack/unpack through the real
    protocol (so tier-1 tests cover the marshalling end to end), but
    dispatch runs immediately against the wrapped engine. `latency_polls`
    defers async completion by that many done() polls — the
    deterministic model of a transfer overlapping the decode pump."""

    def __init__(self, engine, retry=None, latency_polls=0):
        super().__init__(retry)
        self.engine = engine
        self.exports = []
        self.latency_polls = int(latency_polls)

    def _roundtrip(self, kind, meta, payload):
        k, m, p = unpack_frame(pack_frame(kind, meta, payload))
        rk, rm, rp = serve_request(self.engine, k, m, p,
                                   exports=self.exports)
        return unpack_frame(pack_frame(rk, rm, rp))

    def call_async(self, kind, meta=None, payload=b""):
        fut = TransportFuture(polls=(self.latency_polls
                                     if kind == "import_kv" else 0),
                              kind=kind)
        try:
            reply = self._guarded_send(
                kind, lambda: self._roundtrip(kind, meta, payload))
        except TransportError as e:
            fut._fail(e)
            return fut
        # network chaos: a delayed reply lands a beat late; a stalled
        # one is held hostage for a gray-failure window — the loopback
        # model of a saturated NIC or a paused peer. The dispatch above
        # already HAPPENED worker-side; only the reply is late, which is
        # exactly what makes gray failures nastier than crashes.
        hold = 0.0
        if check("mesh.net_delay"):
            hold = _NET_DELAY_S
        if check("mesh.net_stall"):
            hold = _NET_STALL_S
        if hold > 0.0:
            fut._ready_at = time.perf_counter() + hold
        self._settle(fut, reply)
        return fut


class SocketClient(_ClientBase):
    """One serial-ordered socket to a worker process. Requests are
    pipelined: call_async ships the frame now and the reply is drained
    later (replies arrive in request order, so the oldest pending future
    completes first) — the transport copy genuinely overlaps whatever
    the parent does between polls."""

    def __init__(self, sock, retry=None):
        super().__init__(retry)
        self.sock = sock
        self._pending: deque[TransportFuture] = deque()
        self._rxbuf = bytearray()     # partial frames survive a timeout
        self._stall_until = 0.0       # mesh.net_stall hostage window

    def call_async(self, kind, meta=None, payload=b""):
        fut = TransportFuture(client=self, kind=kind)
        try:
            self._guarded_send(
                kind, lambda: send_frame(self.sock, kind, meta, payload))
        except TransportError as e:
            fut._fail(e)
            return fut
        self._pending.append(fut)
        return fut

    def _pop_frame(self):
        """One complete frame parsed off the receive buffer, else None.
        A truncated tail STAYS buffered — a timed-out wait never loses
        mid-frame bytes, so the late reply is still whole when the next
        drain resumes it (the round-20 blocking recv_frame could only
        hang or tear here)."""
        buf = self._rxbuf
        if len(buf) < 12:
            return None
        magic, hlen, plen = struct.unpack_from("<4sII", buf, 0)
        if magic != _MAGIC:
            raise TransportError(f"bad frame magic {magic!r}")
        end = 12 + hlen + plen
        if len(buf) < end:
            return None
        frame = bytes(buf[:end])
        del buf[:end]
        return unpack_frame(frame)

    def _fatal(self, err, cause=None):
        """Hard transport death (peer closed, torn stream): every owed
        reply is unrecoverable — fail them all. Deadline expiry NEVER
        comes through here."""
        if cause is not None:
            err.__cause__ = cause
        while self._pending:
            self._pending.popleft()._fail(err)
        raise err

    def _drain(self, block, deadline=None):
        """Settle owed replies. Non-blocking: consume whatever the
        kernel already holds. Blocking: wait in short select slices
        until ONE reply settles or `deadline` (absolute perf_counter)
        passes — expiry raises typed TransportTimeout with `_pending`
        PRESERVED (the worker is gray; its replies are still owed and
        the serial order still holds)."""
        import select
        while self._pending:
            try:
                frame = self._pop_frame()
            except TransportError as e:
                self._fatal(e)
            if frame is not None:
                self._settle(self._pending.popleft(), frame)
                if block:
                    return
                continue
            now = time.perf_counter()
            if deadline is not None and now >= deadline:
                op = self._pending[0]._kind or "unknown"
                _metric("mesh_rpc_timeouts_total", op=op).inc()
                raise TransportTimeout(
                    f"reply for {op!r} not delivered within the op "
                    "budget (worker slow or stalled; replies stay "
                    "owed — gray, not dead)")
            wait = 0.0
            if block:
                wait = _DRAIN_SLICE_S
                if deadline is not None:
                    wait = min(wait, max(0.0, deadline - now))
            if self._stall_until > now:
                # a hostage reply (mesh.net_stall): refuse to read until
                # the stall lifts — bytes wait in the kernel buffer,
                # exactly a paused peer from this side of the wire
                if not block:
                    return
                time.sleep(min(max(wait, 0.0005),
                               self._stall_until - now))
                continue
            ready, _w, _x = select.select([self.sock], [], [], wait)
            if ready and check("mesh.net_delay"):
                ready = []      # this poll sees nothing (late packet)
            if ready and check("mesh.net_stall"):
                self._stall_until = now + _NET_STALL_S
                ready = []
            if not ready:
                if not block:
                    return
                continue
            try:
                data = self.sock.recv(65536)
            except _TRANSIENT as e:
                self._fatal(
                    TransportError(f"transport receive failed: {e!r}"),
                    cause=e)
            if not data:
                self._fatal(TransportError("peer closed mid-stream"))
            self._rxbuf += data

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# --- the engine duck-type over a client ------------------------------------

class _Stub:
    """Occupancy mirror entry: what the mesh-wide admission view and the
    router's load ranking actually read off a replica engine."""

    __slots__ = ("tenant", "priority", "generated")

    def __init__(self, tenant="-", priority="interactive"):
        self.tenant = tenant
        self.priority = priority
        self.generated = []


class _PoolStub:
    __slots__ = ("block_size",)

    def __init__(self, block_size):
        self.block_size = int(block_size)


class _RemoteFinished:
    """Finished-request record rehydrated from a step reply — the fields
    the router commit, the load harness, and mesh reports consume."""

    __slots__ = ("rid", "generated", "done", "finish_reason", "tenant",
                 "priority", "trace_id", "t_arrival", "t_first",
                 "deadline_s", "shed_count")

    def __init__(self, d):
        self.rid = d["rid"]
        self.generated = list(d["generated"])
        self.done = True
        self.finish_reason = d["finish_reason"]
        self.tenant = d["tenant"]
        self.priority = d["priority"]
        self.trace_id = d["trace_id"]
        self.t_arrival = d["t_arrival"]
        self.t_first = d["t_first"]
        self.deadline_s = d["deadline_s"]
        self.shed_count = d["shed_count"]


class EngineProxy:
    """The ContinuousBatchingEngine duck-type the MeshRouter drives,
    backed by a transport client. State the router reads synchronously
    (queue/lanes/_preempted occupancy, finished, svc, brownout) mirrors
    from the last step reply; mutations (add_request, adopt_identity,
    import_kv) are framed calls. A dead transport latches `lost`: the
    proxy stops accepting work (“BackpressureError” on admit, has_work
    False) and fires on_lost once so the pool can tombstone the lease —
    from the router's point of view, exactly a killed replica."""

    def __init__(self, client, vocab, block_size, name="worker",
                 op_timeout_s=None):
        self.client = client
        self.name = name
        self.queue = []
        self.lanes = []
        self._preempted = {}
        self.finished = {}
        self.prefill_sink = None
        self.scheduler = None
        self.brownout_level = 0
        self.lost = False
        self.on_lost = None
        self.embed_w = np.zeros((int(vocab), 1), np.float32)
        self.pool = _PoolStub(block_size)
        self._has_work = False
        self._svc = None
        self.op_timeout_s = (float(flag_value("mesh_rpc_timeout_s"))
                             if op_timeout_s is None
                             else float(op_timeout_s))
        # gray-failure bookkeeping: a step reply that missed its budget
        # is PARKED (resumed next pump so finished streams and exports
        # are never lost); a resource-creating RPC that missed its
        # budget is remembered so the late-admitted work is cancelled
        self._inflight_step = None
        self._abandoned = []

    def _budget(self, deadline_s=None, t_arrival=None):
        """Seconds this op may wait: the per-op flag budget, tightened
        by the request's REMAINING end-to-end deadline (router →
        prefill → handoff → decode all draw from the same clock).
        Clamps at 0 so an already-expired op still ships — the worker
        rejects it typed server-side, which is the contract under test."""
        b = self.op_timeout_s
        if deadline_s is not None:
            rem = (float(deadline_s) if t_arrival is None
                   else (float(t_arrival) + float(deadline_s)
                         - time.perf_counter()))
            b = min(b, max(0.0, rem))
        return b

    def _mark_lost(self):
        if self.lost:
            return
        self.lost = True
        self.queue = []
        self.lanes = []
        self._preempted = {}
        self._has_work = False
        if self.on_lost is not None:
            self.on_lost(self)

    def _reap_abandoned(self):
        """Resolve RPCs whose client-side budget expired: when the late
        reply finally lands with a rid, that work was admitted on the
        worker AFTER the caller gave up — withdraw it so no ghost stream
        decodes (and no pool blocks leak)."""
        if not self._abandoned:
            return
        keep = []
        for fut in self._abandoned:
            if not fut.done():
                keep.append(fut)
                continue
            try:
                reply, _p = fut.result()
            except Exception:   # noqa: BLE001 — the op failed anyway
                continue
            rid = reply.get("rid")
            if rid is not None:
                self.cancel(int(rid))
        self._abandoned = keep

    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                    seed=0, deadline_s=None, tenant="-",
                    priority="interactive"):
        if self.lost:
            raise BackpressureError(f"worker {self.name} lost")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        budget = self._budget(deadline_s)
        meta = {"max_new_tokens": int(max_new_tokens),
                "eos_token_id": eos_token_id, "do_sample": bool(do_sample),
                "temperature": float(temperature), "top_k": int(top_k),
                "top_p": float(top_p), "seed": seed,
                "deadline_s": deadline_s, "tenant": tenant,
                "priority": priority, "deadline": budget}
        fut = self.client.call_async("add_request", meta, prompt.tobytes())
        try:
            reply, _p = fut.result(timeout=budget)
        except TransportTimeout:
            # gray: the worker may still admit late — remember the
            # future so the eventual rid is withdrawn, and fail THIS
            # placement without latching the replica lost
            self._abandoned.append(fut)
            raise BackpressureError(
                f"worker {self.name} add_request timed out") from None
        except TransportError:
            self._mark_lost()
            raise BackpressureError(f"worker {self.name} lost") from None
        self.queue.append(_Stub(tenant, priority))
        self._has_work = True
        return int(reply["rid"])

    def adopt_identity(self, rid, trace_id, t_arrival=None):
        if self.lost:
            return False
        try:
            reply, _p = self.client.call(
                "adopt", {"rid": int(rid), "trace_id": str(trace_id),
                          "t_arrival": t_arrival},
                timeout=self.op_timeout_s)
        except TransportTimeout:
            return False    # late 'adopted' reply drains harmlessly
        except TransportError:
            self._mark_lost()
            return False
        return bool(reply["adopted"])

    def cancel(self, rid):
        """Withdraw one stream on the worker (a hedge loser, or an RPC
        that timed out client-side but landed late). Fire-and-forget:
        the reply settles on a later drain, and a lost transport needs
        no withdrawal — the work died with the process."""
        if self.lost:
            return False
        self.client.call_async("cancel", {"rid": int(rid)})
        return True

    def import_kv(self, record):
        """Synchronous wire import; rejection rehydrates typed
        (ValueError / MemoryError) so hand_off's classification is
        unchanged; a dead transport surfaces TransportError (transient
        by construction). The remaining request deadline rides the
        frame: an import that lands expired is refused server-side
        (TransportTimeout here → transfer-failure → re-prefill)."""
        if self.lost:
            raise TransportError(f"worker {self.name} lost")
        budget = self._budget(record.get("deadline_s"),
                              record.get("t_arrival"))
        fut = self.client.call_async("import_kv", {"deadline": budget},
                                     pack_record(record))
        try:
            reply, _p = fut.result(timeout=budget)
        except TransportTimeout:
            self._abandoned.append(fut)     # late import = ghost stream
            raise
        except TransportError:
            self._mark_lost()
            raise
        self._has_work = True
        return int(reply["rid"])

    def import_kv_async(self, record):
        """Asynchronous wire import: the frame ships now, the future
        completes on delivery — the decode pump keeps running while the
        copy is in flight."""
        if self.lost:
            fut = TransportFuture()
            fut._fail(TransportError(f"worker {self.name} lost"))
            return fut
        budget = self._budget(record.get("deadline_s"),
                              record.get("t_arrival"))
        fut = self.client.call_async("import_kv", {"deadline": budget},
                                     pack_record(record))
        self._has_work = True
        return fut

    def step(self):
        """One worker step; returns the WORKER-side wall seconds (the
        honest per-chip cost for the simulated-parallel clock — parent
        IPC overhead excluded on purpose). A reply that misses the op
        budget is PARKED and resumed next pump (replies are serial, so
        nothing is reordered): the pump reports no progress, the health
        detector accrues suspicion, and no finished stream or export is
        ever dropped."""
        if self.lost:
            return 0.0
        self._reap_abandoned()
        fut = self._inflight_step
        self._inflight_step = None
        if fut is None:
            fut = self.client.call_async("step")
            budget = self.op_timeout_s
        else:
            # resuming a parked reply: poll one short slice only — the
            # pump must keep cycling so the health detector can accrue
            # suspicion on this replica instead of the router blocking
            budget = min(self.op_timeout_s, _DRAIN_SLICE_S)
        try:
            reply, blob = fut.result(timeout=budget)
        except TransportTimeout:
            self._inflight_step = fut
            return 0.0
        except TransportError:
            self._mark_lost()
            return 0.0
        self.queue = [_Stub(t, p) for t, p in reply["queue"]]
        self.lanes = [None if t is None else _Stub(t)
                      for t in reply["lanes"]]
        self._preempted = {int(rid): (_Stub(t), None, None)
                           for rid, t in reply["preempted"]}
        self._has_work = bool(reply["has_work"])
        self._svc = reply["svc"]
        self.brownout_level = int(reply["brownout_level"])
        for d in reply["finished"]:
            self.finished[int(d["rid"])] = _RemoteFinished(d)
        off = 0
        for size in reply["export_sizes"]:
            (n,) = struct.unpack_from("<I", blob, off)
            assert n == size
            rec = unpack_record(blob[off + 4:off + 4 + n])
            off += 4 + n
            if self.prefill_sink is not None:
                self.prefill_sink(rec)
        return float(reply["dt"])

    def has_work(self):
        return not self.lost and self._has_work

    def predicted_service_seconds(self, output_tokens=32):
        return self._svc

    def predicted_costs(self):
        if self.lost:
            return {}
        try:
            reply, _p = self.client.call("snapshot",
                                         timeout=self.op_timeout_s)
        except TransportTimeout:
            return {}   # advisory data: stale beats blocking the pump
        except TransportError:
            self._mark_lost()
            return {}
        return reply["costs"]

    def shutdown(self):
        if self.lost:
            return
        try:
            self.client.call("shutdown", timeout=self.op_timeout_s)
        except TransportError:
            pass
        self.client.close()


# --- process-backed replicas ------------------------------------------------

class ProcessReplica(Replica):
    """A Replica whose engine is an EngineProxy. step() trusts the
    worker-reported wall (the per-chip cost) and a lost transport walks
    the same death path as pool.kill."""

    __slots__ = ("proc",)

    def __init__(self, name, proxy, role="both", proc=None, **kw):
        super().__init__(name, proxy, role=role, **kw)
        self.proc = proc
        proxy.on_lost = self._on_lost

    def _on_lost(self, _proxy):
        self.alive = False
        for _ in range(self.breaker.failure_threshold):
            self.breaker.record_failure()

    def step(self):
        if not self.engine.has_work():
            return 0.0
        dt = self.engine.step()
        if dt > 0.0:
            self.step_seconds += dt
            self.steps += 1
        return dt


def _spawn_worker(name, spec, listener, worker_env=None):
    """Launch one worker child (two_proc_worker idiom: plain
    sys.executable subprocess, CPU-pinned jax) and accept its transport
    connection. Returns (proc, sock, hello-meta)."""
    specfile = tempfile.NamedTemporaryFile(
        mode="w", suffix=f".{name}.json", delete=False)
    json.dump(spec, specfile)
    specfile.close()
    host, port = listener.getsockname()[:2]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if worker_env:
        env.update(worker_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.inference.mesh.worker",
         "--connect", f"{host}:{port}", "--name", name,
         "--spec", specfile.name],
        env=env, cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))))
    accept_timeout = spec.get("accept_timeout_s")
    if accept_timeout is None:
        accept_timeout = flag_value("mesh_worker_accept_timeout_s")
    listener.settimeout(float(accept_timeout))
    try:
        sock, _addr = listener.accept()
    except socket.timeout:
        proc.kill()
        raise TransportTimeout(
            f"worker {name} never connected within "
            f"{float(accept_timeout):g}s (accept expiry)") from None
    finally:
        try:
            os.unlink(specfile.name)
        except OSError:
            pass
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    client = SocketClient(sock)
    hello, _p = client.call("ping", timeout=float(accept_timeout))
    return proc, client, hello


class ProcessReplicaPool(ReplicaPool):
    """A ReplicaPool whose workers live behind the frame transport.

    transport="loopback": engines are built in-process by
    `build_engine` and wrapped in LoopbackClient proxies — every frame
    marshals through the real protocol, deterministically (tier-1
    shape). Membership is the parent-held lease per replica, exactly
    like ReplicaPool; `threaded_beats=True` switches those leases to
    ElasticManager.start() daemon heartbeats and makes pool.beat() a
    no-op (beat failures are counted, never raised into serving).

    transport="socket": each worker is a CHILD PROCESS (worker.py)
    running a full engine built from `engine_spec` (a JSON-safe dict —
    callables cannot cross a process boundary). The worker registers
    its OWN lease over the shared native TCPStore and runs threaded
    heartbeats; the parent keeps an unregistered manager per replica
    purely to read membership and write the tombstone on kill.
    """

    def __init__(self, build_engine=None, n=2, transport="loopback",
                 engine_spec=None, threaded_beats=False, latency_polls=0,
                 client_retry="default", worker_env=None,
                 op_timeout_s=None, **kw):
        if transport not in ("loopback", "socket"):
            raise ValueError(f"unknown transport {transport!r}")
        if transport == "socket" and engine_spec is None:
            raise ValueError("socket transport needs engine_spec "
                             "(a callable cannot cross a process)")
        if transport == "loopback" and build_engine is None:
            raise ValueError("loopback transport needs build_engine")
        self.transport = transport
        self.engine_spec = engine_spec
        self.threaded_beats = bool(threaded_beats)
        self.latency_polls = int(latency_polls)
        self.worker_env = worker_env
        self.op_timeout_s = op_timeout_s    # None -> FLAGS_mesh_rpc_timeout_s
        self._client_retry = (RetryPolicy(
            max_attempts=3, base_delay=0.001, max_delay=0.01, seed=0,
            sleep=lambda _s: None) if client_retry == "default"
            else client_retry)
        self._listener = None
        if transport == "socket":
            self._listener = socket.socket()
            self._listener.bind(("127.0.0.1", 0))
            self._listener.listen(16)
            build_engine = build_engine or (lambda: None)
        super().__init__(build_engine, n=n, **kw)
        if self.threaded_beats or self.transport == "socket":
            # parent-held leases beat on daemon threads (loopback); the
            # socket workers' own managers already started theirs
            for rep in self.replicas:
                if rep.manager is not None and rep.manager._registered:
                    rep.manager.start()

    # ReplicaPool builds replicas through this hook (round 20 refactor)
    def _make_replica(self, i, role, failure_threshold, reset_timeout):
        name = f"replica{i}"
        if self.transport == "loopback":
            engine = self._build_one_engine()
            proxy = EngineProxy(
                LoopbackClient(engine, retry=self._client_retry,
                               latency_polls=self.latency_polls),
                vocab=engine.embed_w.shape[0],
                block_size=engine.pool.block_size, name=name,
                op_timeout_s=self.op_timeout_s)
            if role == "prefill":
                # prefill workers export instead of decoding locally;
                # records buffer worker-side and ride the step reply —
                # delivery is via the frame protocol, like a process
                self._wire_loopback_sink(engine, proxy)
            return ProcessReplica(name, proxy, role=role,
                                  failure_threshold=failure_threshold,
                                  reset_timeout=reset_timeout)
        spec = dict(self.engine_spec)
        spec["role"] = role
        spec["node_id"] = name
        spec["store"] = {"host": "127.0.0.1", "port": int(self.store.port),
                         "heartbeat_interval": self._hb_interval}
        proc, client, hello = _spawn_worker(name, spec, self._listener,
                                            self.worker_env)
        client._retry = self._client_retry
        proxy = EngineProxy(client, vocab=hello["vocab"],
                            block_size=hello["block_size"], name=name,
                            op_timeout_s=self.op_timeout_s)
        return ProcessReplica(name, proxy, role=role, proc=proc,
                              failure_threshold=failure_threshold,
                              reset_timeout=reset_timeout)

    @staticmethod
    def _wire_loopback_sink(engine, proxy):
        client = proxy.client

        def _sink(record):
            client.exports.append(record)
        engine.prefill_sink = _sink

    def _bind_membership(self, rep, n):
        if self.transport == "socket":
            # the WORKER owns its lease (registered + threaded beats in
            # the child); the parent manager stays unregistered — used
            # only to read alive_nodes and compute the tombstone key
            rep.manager = ElasticManager(
                self.store, node_id=rep.name, np_range=(1, n),
                heartbeat_interval=self._hb_interval,
                retry_policy=self._retry)
            return
        super()._bind_membership(rep, n)

    def beat(self):
        if self.threaded_beats or self.transport == "socket":
            return      # daemon beat threads own the leases
        super().beat()

    def kill(self, name):
        rep = self.by_name(name)
        if rep.alive and rep.proc is not None:
            rep.proc.kill()     # SIGKILL: the real mid-decode death
            rep.proc.wait(timeout=30)
        if self.transport == "socket" and rep.alive:
            # the dead child cannot tombstone itself; the parent writes
            # the empty lease so membership converges immediately
            self.store.set(ElasticManager.PREFIX + name, b"")
            rep.alive = False
            for _ in range(rep.breaker.failure_threshold):
                rep.breaker.record_failure()
            return rep
        return super().kill(name)

    def retire(self, name):
        rep = super().retire(name)
        eng = rep.engine
        if isinstance(eng, EngineProxy):
            eng.shutdown()
        if self.transport == "socket":
            self.store.set(ElasticManager.PREFIX + name, b"")
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
        return rep

    def spawn(self, role="both"):
        rep = super().spawn(role=role)
        if (self.threaded_beats or self.transport == "socket") \
                and rep.manager is not None and rep.manager._registered:
            rep.manager.start()
        return rep

    def close(self):
        for rep in self.replicas:
            if rep.alive:
                try:
                    self.retire(rep.name)
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
        if self._listener is not None:
            self._listener.close()
