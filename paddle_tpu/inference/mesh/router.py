"""Headroom-aware request router for the disaggregated serving mesh.

The MeshRouter fronts a ReplicaPool with the same duck-type surface the
load harness drives a single engine through (add_request / step /
has_work / finished / predicted_service_seconds / predicted_costs), so
`loadgen.run_scenario(router, ...)` works unchanged — the mesh IS an
engine from the harness's point of view.

Routing: requests queue at the router and place onto replicas in DRR
order when an SLOScheduler is attached (the PR-11 priority/tenant
machinery over a mesh-wide admission view), FIFO otherwise. Replica
choice ranks candidates by exported slo_headroom (1 - offered rate x
predicted_service_seconds, per replica) with queue/lane load as the
uncalibrated tiebreaker; every pick passes the `mesh.route` fault site
and the target's CircuitBreaker — a fault or open breaker fails the
pick over to the next-best replica and counts a failover.

Disaggregation: prefill-role replicas carry a prefill_sink, so a
routed request prefills there, exports its paged-KV blocks, and the
router delivers the serialized record to a decode replica
(handoff.hand_off -> import_kv) with retry-then-re-prefill semantics.
The transfer is host bytes between engine steps, overlapped with the
decode replica's in-flight double-buffered tiles.

Correctness contract: tokens commit to the mesh result AT MOST ONCE per
stream — a stream is committed only when it finishes on some replica,
and a mesh request is never committed twice (kill a replica mid-decode
and the re-routed re-prefill regenerates the same stream: greedy decode
is deterministic, sampled lanes key the device PRNG on (seed, absolute
position)). Greedy mesh streams are byte-identical to a single-replica
run (test-pinned).

Gray failure (round 21): slowness and death are distinct signals. A
HealthDetector (health.py) scores every replica's progress per pump —
a busy replica whose counters stop moving accrues phi-style suspicion,
trips SLOW (demoted out of `_ranked`, no new placements, counted) and
only past a much larger threshold DEAD (the existing replica_down
path). Placements that outlive a latency budget (quantile of observed
service via THE shared estimator) are HEDGED: a speculative duplicate
starts on the next-best replica, first finish wins through the same
at-most-once commit map, and the loser is withdrawn (engine.cancel).
Streams parked mid-handoff past their deadline_s finish reason=timeout
here — the one place that can see them (neither engine holds the
stream while its bytes are on the wire).

Simulated-parallel clock: replicas are in-process workers stepped
round-robin, so real wall time is serial. Each pump records every
replica's step wall; `sim_parallel_wall_s` sums the per-round MAXIMUM —
the wall clock N separate chips stepping concurrently would see — and
is labeled as simulated wherever it is reported (bench scaling row).
"""

from __future__ import annotations

import time
from collections import deque

from ...observability.autoscale import AutoscaleAdvisor
from ...observability.catalog import metric as _metric
from ...observability.federation import MeshCollector
from ...observability.metrics import get_registry as _get_registry
from ...observability.recorder import get_recorder as _get_recorder
from ...observability.tracing import get_tracer as _get_tracer
from ...observability.tracing import new_trace_id as _new_trace_id
from ...resilience.faults import FaultInjected, check, fault_point
from ...resilience.retry import RetryPolicy
from ..prefix_cache import affinity_key
from ..serving import BackpressureError
from ..scheduler import PRIORITY_CLASSES
from .handoff import KVHandoffError, hand_off_async
from .health import HealthDetector, LatencyBudget

__all__ = ["MeshRequest", "MeshRouter"]

_TRANSIENT = (TimeoutError, ConnectionError, OSError, FaultInjected)

# prefix-affinity hint bounds: remembered first-chunk hashes (FIFO
# evicted past the cap) and how much extra backlog the remembered
# replica may carry versus the best-ranked candidate before load
# balance wins over cache warmth
_AFFINITY_CAP = 512
_AFFINITY_SLACK = 2


class MeshRequest:
    """One stream tracked mesh-wide: the original admission parameters
    (identity survives re-routing: trace id, sampling seed, arrival
    anchor) plus routing state. Doubles as the finished record for
    requests that never reach a replica (router-side timeout), so it
    carries the same reporting fields a serving Request does."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "do_sample", "temperature", "top_k", "top_p", "seed",
                 "deadline_s", "tenant", "priority", "trace_id",
                 "t_arrival", "t_deadline", "t_first", "generated",
                 "done", "finish_reason", "phase", "replica",
                 "local_rid", "hops", "force_local", "t_placed",
                 "hedges", "adapter")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 do_sample, temperature, top_k, top_p, seed, deadline_s,
                 tenant, priority, adapter=None):
        import numpy as np
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.tenant = str(tenant) if tenant else "-"
        self.priority = priority
        self.trace_id = _new_trace_id("req-")
        self.t_arrival = time.perf_counter()
        self.t_deadline = (None if self.deadline_s is None
                           else self.t_arrival + self.deadline_s)
        self.t_first = None
        self.generated = []
        self.done = False
        self.finish_reason = None
        self.phase = "queued"       # queued -> placed -> handoff -> done
        self.replica = None
        self.local_rid = None
        self.hops = 0               # times routed (1 = no failover)
        self.force_local = False    # re-prefill fallback: serve fully
                                    # on a decode replica, no handoff
        self.t_placed = None        # when the live placement started
        self.hedges = []            # speculative duplicate placements:
                                    # [(replica name, local rid), ...]
        self.adapter = str(adapter) if adapter else None


class _AdmissionView:
    """The mesh-wide facade SLOScheduler.pick_index walks: the router's
    front queue plus every alive replica's lanes and parked requests,
    so tenant lane quotas count cluster-wide occupancy."""

    __slots__ = ("queue", "lanes", "_preempted")

    def __init__(self, router):
        self.queue = router.queue
        self.lanes = []
        self._preempted = {}
        for rep in router.pool.alive():
            self.lanes.extend(rep.engine.lanes)
            self._preempted.update(rep.engine._preempted)


class MeshRouter:
    """router = MeshRouter(ReplicaPool(build_engine, n=2))
    rid = router.add_request(prompt, max_new_tokens=16)
    streams = router.run()          # {mesh rid: [tokens]}
    """

    def __init__(self, pool, scheduler=None, max_queue=None,
                 handoff_retry=None, collector="auto", advisor=None,
                 health="auto", hedge_budget_s="auto"):
        self.pool = pool
        self.scheduler = scheduler  # admission ORDER only (DRR pick);
                                    # per-replica brownout stays on the
                                    # replicas' own schedulers
        self.max_queue = None if max_queue is None else int(max_queue)
        self.queue: deque[MeshRequest] = deque()
        self.finished: dict[int, object] = {}   # mesh rid -> Request-like
        self._next_rid = 0
        self._open: dict[int, MeshRequest] = {}
        self._by_trace: dict[str, MeshRequest] = {}
        # (replica name, local rid) -> MeshRequest: the commit map the
        # harvest walks; first finish wins (at-most-once commit)
        self._local: dict[tuple[str, int], MeshRequest] = {}
        self._handoff_q: deque[dict] = deque()
        # in-flight asynchronous deliveries: (future, record, replica
        # name, names already tried) — the decode side parks the stream
        # only on delivery-complete; until then the pump keeps running
        self._pending_handoffs: list[tuple] = []
        # round 20: a MeshController (controller.py) acts on autoscale
        # verdicts when attached; None keeps the advisor advisory-only
        self.controller = None
        self._retry = handoff_retry if handoff_retry is not None else \
            RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.01,
                        seed=0, sleep=lambda _s: None)
        self._handoffs = {"ok": 0, "retried": 0, "re_prefill": 0,
                          "bytes": 0}
        # round 18: prefix-affinity hint — first-prompt-chunk hash ->
        # replica that last served it, so requests sharing a system
        # prompt land where the prefix index is already warm. A HINT
        # only: consulted when the remembered replica is a live
        # candidate whose backlog is within _AFFINITY_SLACK of the
        # best-ranked one; bounded FIFO map, never a correctness input.
        self._affinity: dict[bytes, str] = {}
        self._affinity_bs = int(pool[0].engine.pool.block_size)
        self._failovers: dict[str, int] = {}
        # round 21: gray-failure machinery. The detector scores every
        # replica's progress each pump (SLOW names sit in _slow and are
        # demoted from _ranked); the LatencyBudget turns observed
        # placed->commit service into the hedging trigger.
        # health: "auto" -> HealthDetector(), None -> off, or a
        # preconfigured detector (drills tighten its thresholds).
        # hedge_budget_s: "auto" -> quantile budget, None -> hedging
        # off, float -> fixed budget (tests pin it).
        self.health = HealthDetector() if health == "auto" else health
        self._slow: set[str] = set()
        self._hedge_budget_s = hedge_budget_s
        self._service = LatencyBudget()
        self._arrivals: deque[float] = deque(maxlen=256)
        self._t0 = time.perf_counter()
        self.sim_parallel_wall_s = 0.0
        self.serial_wall_s = 0.0
        self.rounds = 0
        self._rec = _get_recorder()
        self._tracer = _get_tracer()
        # bind export sinks on the prefill workers (disaggregated pools
        # only; "both"-role replicas serve locally end to end)
        if pool.disaggregate:
            for rep in pool:
                if rep.role == "prefill":
                    rep.engine.prefill_sink = self._sink
        self.embed_w = pool[0].engine.embed_w
        # round 17: the mesh observability plane. "auto" attaches a
        # MeshCollector only when the observability layer is on, so a
        # disabled-plane mesh (most tests, chaos drills) pays nothing —
        # the drilled no-op contract. The advisor turns the collector's
        # recording rules into the autoscale verdict mesh_report() emits.
        if collector == "auto":
            collector = (MeshCollector(pool)
                         if _get_registry().enabled else None)
        self.collector = collector
        self.advisor = advisor if advisor is not None else (
            AutoscaleAdvisor() if collector is not None else None)
        self._autoscale_verdict = None

    # --- harness-facing engine surface -----------------------------------
    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                    seed=0, deadline_s=None, tenant="-",
                    priority="interactive", adapter=None):
        """Queue a request at the mesh front door. Same contract as the
        engine's add_request (priority registry, BackpressureError at
        max_queue); returns the MESH rid."""
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority class {priority!r}; registered: "
                f"{list(PRIORITY_CLASSES)}")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            _metric("serving_backpressure_total").inc()
            raise BackpressureError(
                f"mesh front queue full ({len(self.queue)}/"
                f"{self.max_queue}); retry later")
        rid = self._next_rid
        self._next_rid += 1
        mreq = MeshRequest(rid, prompt, max_new_tokens, eos_token_id,
                           do_sample, temperature, top_k, top_p, seed,
                           deadline_s, tenant, priority, adapter=adapter)
        self.queue.append(mreq)
        self._open[rid] = mreq
        self._by_trace[mreq.trace_id] = mreq
        self._arrivals.append(mreq.t_arrival)
        return rid

    def has_work(self):
        return bool(self.queue or self._handoff_q
                    or self._pending_handoffs
                    or any(not m.done for m in self._open.values()))

    def step(self):
        """One mesh pump: membership beat + kill checks, failover of
        dead replicas' streams, routing, one step per alive replica
        (per-round max wall feeds the simulated-parallel clock),
        handoff delivery, and the commit harvest."""
        self.pool.beat()
        # behavioral kill site: the chaos drill arms mesh.replica_down
        # and the Nth pump loses a worker, exactly like a process kill
        if check("mesh.replica_down") and len(self.pool.alive()) > 1:
            self.kill_replica(self.pool.alive()[0].name, why="injected")
        self._expire_queued()
        self._failover_dead()
        self._route()
        dts = [rep.step() for rep in self.pool.alive()]
        busy = [dt for dt in dts if dt > 0.0]
        if busy:
            self.sim_parallel_wall_s += max(busy)
            self.serial_wall_s += sum(busy)
            self.rounds += 1
        self._observe_health()
        self._pump_handoffs()
        self._maybe_hedge()
        self._harvest()
        if self.collector is not None:
            # sample the plane LAST so the tick sees this pump's state;
            # a collector failure degrades the plane, never the pump
            self.collector.tick()
            if self.advisor is not None:
                self._autoscale_verdict = self._advise()
        if self.controller is not None:
            # the controller acts AFTER harvest so its idle/drained
            # reads are stable; any failure latches it advisory-only
            self.controller.act(self._autoscale_verdict)

    def run(self, max_steps=10_000):
        """Drive to completion; {mesh rid: [tokens]}."""
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return {rid: list(r.generated)
                for rid, r in sorted(self.finished.items())}

    def predicted_service_seconds(self, output_tokens=32):
        """Mesh-level capacity: mean per-replica calibrated service
        seconds divided by the number of alive replicas that could take
        the work — N workers serve N requests in one replica's time.
        None until at least one replica's cost model calibrates."""
        reps = self.pool.alive()
        ts = [t for t in (rep.engine.predicted_service_seconds(
            output_tokens=output_tokens) for rep in reps)
            if t is not None]
        if not ts:
            return None
        return (sum(ts) / len(ts)) / max(1, len(reps))

    def predicted_costs(self):
        """Per-replica program costs, replica-prefixed."""
        out = {}
        for rep in self.pool.alive():
            for key, cost in rep.engine.predicted_costs().items():
                out[f"{rep.name}:{key}"] = cost
        return out

    # --- routing ---------------------------------------------------------
    def _offered_rate(self):
        now = time.perf_counter()
        win = 0.5
        recent = sum(1 for t in self._arrivals if t > now - win)
        return recent / win

    def _ranked(self, reps):
        """Candidates best-first: lightest observed backlog (queued +
        occupied + parked — immune to cost-model noise, guarantees
        balance across identical replicas), then predicted time-to-
        drain (calibrated service seconds x backlog; uncalibrated
        replicas priced at the calibrated mean, 1s cold, so new workers
        still draw traffic and calibrate), then name. The slo_headroom
        gauge (1 - offered rate x svc) is exported per pick."""
        # controller scale-down victims and SLOW-demoted (health
        # detector) replicas take no NEW work — unless they are all
        # that's left (hint, never a wall)
        active = ([r for r in reps
                   if not r.draining and r.name not in self._slow]
                  or [r for r in reps if not r.draining] or reps)
        rate = self._offered_rate() / max(1, len(active))
        svcs = {rep: rep.engine.predicted_service_seconds()
                for rep in active}
        known = [s for s in svcs.values() if s is not None]
        fallback = sum(known) / len(known) if known else 1.0
        scored = []
        for rep in active:
            svc = svcs[rep]
            if svc is not None:
                _metric("mesh_replica_headroom",
                        replica=rep.name).set(1.0 - rate * svc)
            drain = (svc if svc is not None else fallback) \
                * (rep.load() + 1)
            scored.append((rep, drain))
        # browned-out replicas demote between load and drain-time: a
        # routing HINT (deterministic tiebreak), never a correctness
        # input — a fully browned-out pool still serves everywhere
        return [rep for rep, _d in sorted(
            scored, key=lambda t: (t[0].load(), t[0].brownout_level(),
                                   t[1], t[0].name))]

    def _failover(self, reason, mreq=None):
        self._failovers[reason] = self._failovers.get(reason, 0) + 1
        _metric("mesh_failovers_total", reason=reason).inc()
        if self._rec.enabled:
            self._rec.record("mesh", action="failover", reason=reason,
                             trace=None if mreq is None else mreq.trace_id)

    @staticmethod
    def _adapter_capable(rep, adapter):
        """Placement gate for adapter-bound requests: the replica's
        store must know the name (resident or hot-loadable). A replica
        whose engine is not introspectable — a process-transport proxy —
        is assumed capable; its own admission rejects typed if not."""
        if not adapter:
            return True
        try:
            store = getattr(rep.engine, "adapters", None)
        except Exception:  # noqa: BLE001 — proxy attribute access
            return True
        if store is None or not hasattr(store, "can_serve"):
            # storeless engines reject typed at admission; proxies that
            # hide the attribute are assumed capable
            return not hasattr(rep.engine, "lanes")
        return bool(store.can_serve(adapter))

    def _place(self, mreq):
        """Try to place one mesh request on a replica; True on success.
        Targets the prefill pool for disaggregated requests, the decode
        pool for re-prefill fallbacks, everything alive otherwise."""
        if self.pool.disaggregate and not mreq.force_local:
            cands = self.pool.prefill_targets() or self.pool.decode_targets()
        elif mreq.force_local:
            # re-prefill fallback: a decode replica serves the stream
            # end to end (role is routing policy; every worker can)
            cands = self.pool.decode_targets() or self.pool.alive()
        else:
            cands = self.pool.alive()
        ranked = self._ranked(cands)
        akey = affinity_key("mesh", self._affinity_bs, mreq.prompt)
        if akey is not None and len(ranked) > 1:
            hint = self._affinity.get(akey)
            if hint is not None:
                pref = next((r for r in ranked if r.name == hint), None)
                if (pref is not None and pref is not ranked[0]
                        and pref.load()
                        <= ranked[0].load() + _AFFINITY_SLACK):
                    ranked.remove(pref)
                    ranked.insert(0, pref)
        if mreq.adapter and ranked and not any(
                self._adapter_capable(r, mreq.adapter) for r in ranked):
            # NO alive replica can serve the adapter: typed mesh-level
            # rejection now beats spinning the front queue forever
            self._failover("adapter_missing", mreq)
            _metric("serving_rejected_total", reason="adapter").inc()
            self._commit(mreq, mreq, "rejected")
            return True
        for rep in ranked:
            if not self._adapter_capable(rep, mreq.adapter):
                # adapter affinity: never place on a replica whose store
                # cannot hot-load the name — admission there would only
                # burn a typed rejection. Counted like any other skip.
                self._failover("adapter_missing", mreq)
                continue
            if not rep.breaker.allow():
                self._failover("circuit_open", mreq)
                continue
            try:
                fault_point("mesh.route", rid=mreq.rid, replica=rep.name)
            except _TRANSIENT:
                rep.breaker.record_failure()
                self._failover("route_fault", mreq)
                continue
            try:
                # adapter kwarg only when set: storeless process workers
                # keep their unextended call frame on the wire
                akw = ({"adapter": mreq.adapter} if mreq.adapter else {})
                local_rid = rep.engine.add_request(
                    mreq.prompt, max_new_tokens=mreq.max_new_tokens,
                    eos_token_id=mreq.eos_token_id,
                    do_sample=mreq.do_sample,
                    temperature=mreq.temperature, top_k=mreq.top_k,
                    top_p=mreq.top_p, seed=mreq.seed,
                    deadline_s=mreq.deadline_s, tenant=mreq.tenant,
                    priority=mreq.priority, **akw)
            except BackpressureError:
                self._failover("admit_failed", mreq)
                continue
            rep.breaker.record_success()
            # the replica-local Request adopts the mesh identity so
            # spans, exemplars, and the handoff all join one trace, and
            # TTFT/deadlines stay anchored at TRUE arrival — a framed
            # call for process workers, the same method in-process
            rep.engine.adopt_identity(local_rid, mreq.trace_id,
                                      mreq.t_arrival)
            mreq.phase = "placed"
            mreq.replica = rep.name
            mreq.local_rid = local_rid
            mreq.t_placed = time.perf_counter()
            mreq.hops += 1
            rep.routed += 1
            self._local[(rep.name, local_rid)] = mreq
            if akey is not None:
                self._affinity.pop(akey, None)
                self._affinity[akey] = rep.name
                while len(self._affinity) > _AFFINITY_CAP:
                    self._affinity.pop(next(iter(self._affinity)))
            _metric("mesh_routed_total", replica=rep.name).inc()
            if self._rec.enabled:
                self._rec.record("mesh", action="route", rid=mreq.rid,
                                 replica=rep.name, hop=mreq.hops,
                                 trace=mreq.trace_id)
            if self._tracer.enabled:
                self._tracer.add_span(
                    "mesh.route", time.perf_counter_ns(), 0,
                    trace_id=mreq.trace_id,
                    args={"replica": rep.name, "hop": mreq.hops})
            return True
        return False

    def _route(self):
        """Move front-queue requests onto replicas. With a scheduler,
        admission order is its DRR/priority pick over the mesh-wide
        view; a pick that cannot place anywhere stops routing for this
        pump (ordering is preserved, retried next pump)."""
        while self.queue:
            if self.scheduler is not None:
                idx = self.scheduler.pick_index(_AdmissionView(self))
                if idx is None:
                    return
            else:
                idx = 0
            mreq = self.queue[idx]
            if not self._place(mreq):
                return
            del self.queue[idx]

    def _expire_queued(self):
        """Router-side deadline expiry for requests still in the front
        queue (all replicas saturated / breakers open): same degraded
        'timeout' completion the engine gives its own queue. ALSO sweeps
        streams that exist only between replicas — exported records
        waiting delivery (_handoff_q) and parked async handoffs
        (_pending_handoffs): the prefill engine already released them
        and the decode engine has not admitted them, so neither engine's
        own sweep can see them. A late-landing import for an expired
        stream is withdrawn by _poll_pending's done-cleanup, releasing
        the decode side's blocks."""
        now = time.perf_counter()
        if any(m.t_deadline is not None and now >= m.t_deadline
               for m in self.queue):
            kept = deque()
            for mreq in self.queue:
                if mreq.t_deadline is not None and now >= mreq.t_deadline:
                    self._commit(mreq, mreq, "timeout")
                else:
                    kept.append(mreq)
            self.queue = kept
        for record in list(self._handoff_q) + [e[1] for e
                                               in self._pending_handoffs]:
            mreq = self._by_trace.get(record["trace_id"])
            if (mreq is None or mreq.done or mreq.t_deadline is None
                    or now < mreq.t_deadline):
                continue
            _metric("serving_timeouts_total", where="handoff").inc()
            if self._rec.enabled:
                self._rec.record("timeout", rid=mreq.rid, where="handoff")
            self._commit(mreq, mreq, "timeout")

    # --- disaggregated handoff -------------------------------------------
    def _sink(self, record):
        """prefill_sink bound on prefill workers: the exported record
        queues for delivery on the next pump — i.e. while the decode
        replica's in-flight tiles drain, not blocking either engine."""
        self._handoff_q.append(record)

    def _pump_handoffs(self):
        # poll in-flight deliveries FIRST: any transport copy that
        # completed while the decode pump ran parks its stream now
        if self._pending_handoffs:
            pending, self._pending_handoffs = self._pending_handoffs, []
            for entry in pending:
                self._poll_pending(*entry)
        for _ in range(len(self._handoff_q)):
            record = self._handoff_q.popleft()
            self._deliver(record)

    def _deliver(self, record, tried=None):
        mreq = self._by_trace.get(record["trace_id"])
        if mreq is None or mreq.done:
            return
        tried = set() if tried is None else tried
        rejected = bool(tried)
        for rep in self._ranked(self.pool.decode_targets()):
            if rep.name in tried:
                continue
            if not rep.breaker.allow():
                self._failover("circuit_open", mreq)
                continue
            fut = hand_off_async(record, rep.engine, retry=self._retry)
            if not fut.done():
                # delivery in flight: the transport copy overlaps the
                # decode pump; the stream parks only on completion
                mreq.phase = "handoff_pending"
                self._pending_handoffs.append(
                    (fut, record, rep.name, tried, time.perf_counter()))
                if self._rec.enabled:
                    self._rec.record("mesh", action="handoff_async",
                                     replica=rep.name,
                                     trace=mreq.trace_id)
                return
            try:
                local_rid, nbytes, retries = fut.result()
            except KVHandoffError as e:
                if isinstance(e.__cause__, (ValueError, MemoryError)):
                    # THIS target rejected the record (format mismatch /
                    # pool full) — the transfer itself is fine, try the
                    # next-best decode worker
                    rejected = True
                    tried.add(rep.name)
                    continue
                rep.breaker.record_failure()
                break       # transfer failed past the retry budget
            self._handoff_ok(mreq, rep, local_rid, nbytes, retries)
            return
        self._re_prefill(mreq, rejected)

    def _poll_pending(self, fut, record, rname, tried, t0):
        """Progress one in-flight async handoff; unresolved futures go
        back on the pending list, completed ones settle through the
        same classification as the synchronous path."""
        if not fut.done():
            self._pending_handoffs.append((fut, record, rname, tried, t0))
            return
        mreq = self._by_trace.get(record["trace_id"])
        if mreq is None or mreq.done:
            # the stream no longer needs this import (its hedge sibling
            # committed first, or its deadline expired while parked) —
            # if the copy landed anyway, withdraw the duplicate so the
            # decode side's pool blocks release instead of a ghost
            # stream decoding to nowhere
            self._withdraw_import(fut, record, rname, mreq)
            return
        rep = self.pool.by_name(rname)
        if not rep.alive:
            # the target died with the copy in flight — a transfer
            # failure by definition; failover already re-routed nothing
            # (mreq.replica was never set), so re-prefill here
            self._re_prefill(mreq, bool(tried))
            return
        try:
            local_rid, nbytes, retries = fut.result()
        except KVHandoffError as e:
            if isinstance(e.__cause__, (ValueError, MemoryError)):
                tried.add(rname)
                self._deliver(record, tried=tried)
                return
            rep.breaker.record_failure()
            self._re_prefill(mreq, bool(tried))
            return
        self._handoff_ok(mreq, rep, local_rid, nbytes, retries)

    def _handoff_ok(self, mreq, rep, local_rid, nbytes, retries):
        rep.breaker.record_success()
        self._handoffs["ok"] += 1
        self._handoffs["bytes"] += nbytes
        if retries:
            self._handoffs["retried"] += 1
            _metric("mesh_handoffs_total", outcome="retried").inc()
        _metric("mesh_handoffs_total", outcome="ok").inc()
        _metric("mesh_handoff_bytes").observe(nbytes)
        mreq.phase = "handoff"
        mreq.replica = rep.name
        mreq.local_rid = local_rid
        rep.routed += 1
        self._local[(rep.name, local_rid)] = mreq
        if self._rec.enabled:
            self._rec.record("mesh", action="handoff",
                             replica=rep.name, bytes=nbytes,
                             retries=retries, trace=mreq.trace_id)
        if self._tracer.enabled:
            self._tracer.add_span(
                "mesh.handoff", time.perf_counter_ns(), 0,
                trace_id=mreq.trace_id,
                args={"replica": rep.name, "bytes": nbytes})

    def _re_prefill(self, mreq, rejected):
        # retry-then-re-prefill: the serialized blocks never arrived (or
        # no decode worker could hold them) — re-run prefill from the
        # prompt on the decode side. Slower, byte-identical.
        self._handoffs["re_prefill"] += 1
        _metric("mesh_handoffs_total", outcome="re_prefill").inc()
        self._requeue(mreq, front=True, force_local=True)
        if self._rec.enabled:
            self._rec.record("mesh", action="re_prefill",
                             rejected=rejected, trace=mreq.trace_id)

    def _withdraw_import(self, fut, record, rname, mreq):
        """A landed import whose stream is already settled elsewhere:
        cancel it on the decode worker (idempotent server-side; the
        commit map would drop its tokens anyway — this just stops the
        wasted decode and frees the blocks)."""
        try:
            local_rid, _nbytes, _retries = fut.result()
        except Exception:   # noqa: BLE001 — failed delivery, nothing to undo
            return
        rep = self.pool.by_name(rname)
        cancel = getattr(rep.engine, "cancel", None)
        if rep.alive and cancel is not None:
            # map the duplicate into the commit graveyard FIRST: if the
            # cancel races a same-pump finish, harvest still drops it
            if mreq is not None:
                self._local[(rname, local_rid)] = mreq
            try:
                cancel(local_rid)
            except _TRANSIENT:
                pass
        if self._rec.enabled:
            self._rec.record("mesh", action="import_withdrawn",
                             replica=rname, trace=record.get("trace_id"))

    # --- gray failure: progress health + hedged recovery -----------------
    def _observe_health(self):
        """Feed the detector one observation per alive replica and act
        on the verdict: SLOW demotes (reversibly) out of _ranked, DEAD
        walks the existing replica_down path. Progress is the counters
        that only move when the worker actually answers (steps credited,
        streams harvested, tokens committed) — a worker whose step reply
        is parked past its budget reports dt=0 and freezes all three."""
        if self.health is None:
            return
        now = time.perf_counter()
        for rep in self.pool.alive():
            progress = (rep.steps, rep.finished_count, rep.tokens_out)
            busy = bool(rep.engine.has_work())
            verdict, phi = self.health.observe(rep.name, now, busy,
                                               progress)
            _metric("mesh_replica_suspicion", replica=rep.name).set(phi)
            if verdict == "dead" and len(self.pool.alive()) > 1:
                self._slow.discard(rep.name)
                if self._rec.enabled:
                    self._rec.record("mesh", action="health_dead",
                                     replica=rep.name, phi=round(phi, 2))
                self.kill_replica(rep.name, why="health_dead")
            elif verdict != "healthy" and rep.name not in self._slow:
                # "dead" with no survivor also lands here: demote-only
                # (killing the last replica would serve nobody)
                self._slow.add(rep.name)
                _metric("mesh_slow_demotions_total",
                        replica=rep.name).inc()
                if self._rec.enabled:
                    self._rec.record("mesh", action="health_slow",
                                     replica=rep.name, phi=round(phi, 2))
            elif verdict == "healthy" and rep.name in self._slow:
                self._slow.discard(rep.name)
                if self._rec.enabled:
                    self._rec.record("mesh", action="health_recovered",
                                     replica=rep.name)

    def _hedge_budget(self):
        if self._hedge_budget_s == "auto":
            return self._service.budget()    # None until calibrated
        return self._hedge_budget_s          # None = off, float = fixed

    def _maybe_hedge(self):
        """Speculative duplicates for work that outlived the latency
        budget: a parked handoff whose copy never completes, or an
        in-flight placement stuck on a prefill-role or SLOW replica.
        One hedge per stream; first finish wins through the commit map
        (the loser is withdrawn), so greedy streams stay byte-identical
        whether the original or the hedge lands first."""
        budget = self._hedge_budget()
        if budget is None:
            return
        now = time.perf_counter()
        for _fut, record, rname, _tried, t0 in list(self._pending_handoffs):
            if now - t0 <= budget:
                continue
            mreq = self._by_trace.get(record["trace_id"])
            if mreq is None or mreq.done or mreq.hedges:
                continue
            self._launch_hedge(mreq, exclude={rname})
        for mreq in list(self._open.values()):
            if (mreq.done or mreq.hedges or mreq.phase != "placed"
                    or mreq.replica is None or mreq.t_placed is None
                    or now - mreq.t_placed <= budget):
                continue
            try:
                rep = self.pool.by_name(mreq.replica)
            except KeyError:
                continue
            if not rep.alive:
                continue        # _failover_dead owns dead-replica streams
            if rep.role == "prefill" or rep.name in self._slow:
                self._launch_hedge(mreq, exclude={mreq.replica})

    def _launch_hedge(self, mreq, exclude):
        """Place a full-service duplicate (prompt re-prefill, same
        identity) on the best replica not in `exclude`; True when one
        started. The duplicate adopts the same trace so either finish
        commits the same stream."""
        cands = [r for r in self._ranked(self.pool.decode_targets()
                                         or self.pool.alive())
                 if r.name not in exclude
                 and self._adapter_capable(r, mreq.adapter)]
        for rep in cands:
            if not rep.breaker.allow():
                continue
            try:
                akw = ({"adapter": mreq.adapter} if mreq.adapter else {})
                local_rid = rep.engine.add_request(
                    mreq.prompt, max_new_tokens=mreq.max_new_tokens,
                    eos_token_id=mreq.eos_token_id,
                    do_sample=mreq.do_sample,
                    temperature=mreq.temperature, top_k=mreq.top_k,
                    top_p=mreq.top_p, seed=mreq.seed,
                    deadline_s=mreq.deadline_s, tenant=mreq.tenant,
                    priority=mreq.priority, **akw)
            except BackpressureError:
                continue
            rep.engine.adopt_identity(local_rid, mreq.trace_id,
                                      mreq.t_arrival)
            rep.routed += 1
            mreq.hedges.append((rep.name, local_rid))
            self._local[(rep.name, local_rid)] = mreq
            _metric("mesh_hedges_total", outcome="launched").inc()
            if self._rec.enabled:
                self._rec.record("mesh", action="hedge",
                                 replica=rep.name, trace=mreq.trace_id)
            if self._tracer.enabled:
                self._tracer.add_span(
                    "mesh.hedge", time.perf_counter_ns(), 0,
                    trace_id=mreq.trace_id, args={"replica": rep.name})
            return True
        return False

    def _settle_hedges(self, mreq, winner):
        """First finish won: withdraw every losing placement from its
        worker. The _local entries STAY — if a cancel races a finish,
        harvest pops the duplicate and _commit's idempotence drops it
        unread (the original at-most-once contract)."""
        placements = []
        if mreq.replica is not None and mreq.local_rid is not None:
            placements.append((mreq.replica, mreq.local_rid))
        placements.extend(mreq.hedges)
        if winner is not None and winner in mreq.hedges:
            _metric("mesh_hedges_total", outcome="win").inc()
            if self._rec.enabled:
                self._rec.record("mesh", action="hedge_win",
                                 replica=winner[0], trace=mreq.trace_id)
        for key in placements:
            if key == winner:
                continue
            try:
                rep = self.pool.by_name(key[0])
            except KeyError:
                continue
            cancel = getattr(rep.engine, "cancel", None)
            if not rep.alive or cancel is None:
                continue
            try:
                if cancel(key[1]):
                    _metric("mesh_hedges_total",
                            outcome="cancelled").inc()
                    if self._rec.enabled:
                        self._rec.record("mesh", action="hedge_cancel",
                                         replica=key[0],
                                         trace=mreq.trace_id)
            except _TRANSIENT:
                pass

    # --- failover --------------------------------------------------------
    def kill_replica(self, name, why="drill"):
        """Lose a worker: tombstone its lease (pool.kill) and re-route
        every uncommitted stream it held — each re-prefills from its
        prompt on a survivor and regenerates the same tokens."""
        rep = self.pool.by_name(name)
        if not rep.alive:
            return
        self.pool.kill(name)
        self._slow.discard(name)
        if self.health is not None:
            self.health.forget(name)    # a respawn starts clean
        if self._rec.enabled:
            self._rec.record("mesh", action="kill", replica=name, why=why)
        self._failover_dead()

    def _failover_dead(self):
        """Re-route uncommitted streams assigned to dead replicas, and
        drop exported-but-undelivered handoff records that originated
        on one (they lived in the dead process's memory)."""
        dead = {rep.name for rep in self.pool if not rep.alive}
        if not dead:
            return
        moved = set()
        for (rname, _lrid), mreq in list(self._local.items()):
            if (rname in dead and not mreq.done
                    and mreq.replica == rname
                    and mreq.rid not in moved):
                moved.add(mreq.rid)
                self._failover("replica_down", mreq)
                self._requeue(mreq, front=True,
                              force_local=not self.pool.disaggregate
                              or not self.pool.prefill_targets())
        if self._handoff_q:
            survivors = deque()
            for record in self._handoff_q:
                mreq = self._by_trace.get(record["trace_id"])
                if mreq is not None and not mreq.done \
                        and mreq.rid not in moved:
                    survivors.append(record)
            self._handoff_q = survivors

    def _requeue(self, mreq, front=False, force_local=False):
        mreq.phase = "queued"
        mreq.replica = None
        mreq.local_rid = None
        mreq.force_local = force_local or mreq.force_local
        if front:
            self.queue.appendleft(mreq)
        else:
            self.queue.append(mreq)

    # --- commit (at most once per stream) --------------------------------
    def _commit(self, mreq, rec, reason=None, winner=None):
        if mreq.done:
            return
        mreq.done = True
        mreq.phase = "done"
        if rec is mreq:
            mreq.finish_reason = reason
        self.finished[mreq.rid] = rec
        self._open.pop(mreq.rid, None)
        self._by_trace.pop(mreq.trace_id, None)
        if rec is not mreq and mreq.t_placed is not None:
            # real service only (router-side timeouts would poison the
            # quantile the hedging budget is derived from)
            self._service.observe(time.perf_counter() - mreq.t_placed)
        if mreq.hedges:
            self._settle_hedges(mreq, winner)

    def _harvest(self):
        """Pull finished requests off alive replicas into the mesh
        result. A stream commits exactly once: the commit map's first
        finish wins, later duplicates (a re-routed stream whose original
        replica was thought dead, or a hedge's losing sibling) are
        dropped unread."""
        for rep in self.pool.alive():
            eng = rep.engine
            if not eng.finished:
                continue
            for local_rid in list(eng.finished):
                mreq = self._local.get((rep.name, local_rid))
                if mreq is None:
                    continue
                req = eng.finished.pop(local_rid)
                rep.finished_count += 1
                rep.tokens_out += len(req.generated)
                self._commit(mreq, req, winner=(rep.name, local_rid))

    # --- telemetry aggregation -------------------------------------------
    def _advise(self):
        """One deterministic advisory tick: the collector's recording
        rules (headroom min/sum, burn rate) plus the router's own
        backlog and per-replica snapshots for drain predictions.
        Defaults are benign (full headroom, no burn) until the rules
        have the two ticks they need to evaluate."""
        alive = self.pool.alive()
        col = self.collector
        hm = col.latest("headroom_min")
        hs = col.latest("headroom_sum")
        burn = col.latest("slo_burn_rate")
        return self.advisor.advise(
            current_replicas=len(alive),
            headroom_min=1.0 if hm is None else hm,
            headroom_sum=hs,
            burn_rate=0.0 if burn is None else burn,
            backlog=len(self.queue),
            replica_stats={rep.name: rep.snapshot() for rep in alive})

    def mesh_report(self):
        """One mesh-level report: per-replica phase/SLO snapshots plus
        routing, handoff, failover, and simulated-parallel wall
        accounting. `sim_parallel_wall_s` is the concurrent-worker
        clock (per-round max of the in-process replica step walls) —
        simulated, and labeled as such wherever bench reports it."""
        committed_tokens = sum(len(r.generated)
                               for r in self.finished.values())
        sim = self.sim_parallel_wall_s
        report = {
            "replicas": {rep.name: rep.snapshot() for rep in self.pool},
            "membership": self.pool.alive_nodes(),
            "disaggregate": self.pool.disaggregate,
            "routed": sum(rep.routed for rep in self.pool),
            "handoffs": dict(self._handoffs),
            "failovers": dict(self._failovers),
            "slow": sorted(self._slow),
            "suspicion": ({rep.name: round(self.health.suspicion(
                rep.name, time.perf_counter()), 3)
                for rep in self.pool.alive()}
                if self.health is not None else {}),
            "open": sum(1 for m in self._open.values() if not m.done),
            "committed_tokens": committed_tokens,
            "rounds": self.rounds,
            "serial_wall_s": round(self.serial_wall_s, 4),
            "sim_parallel_wall_s": round(sim, 4),
            "sim_parallel": True,
            "sim_tok_per_s": (round(committed_tokens / sim, 1)
                              if sim > 0 else None),
        }
        if self.collector is not None:
            report["timeseries"] = self.collector.summary()
            if self.advisor is not None:
                report["autoscale"] = (self._autoscale_verdict
                                       if self._autoscale_verdict is not None
                                       else self._advise())
        return report
