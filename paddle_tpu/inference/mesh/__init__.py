"""Disaggregated serving mesh (rounds 16 + 20).

Turns the single-process ContinuousBatchingEngine into a cluster of
worker replicas — in-process or real child processes:

- `replica.ReplicaPool` — N engine replicas (optionally TP-sharded via
  the PR-12 auto-parallel passes) with lease-based membership over
  TCPStore + ElasticManager; killing one tombstones its lease.
- `handoff` — byte-exact serialized paged-KV transfer between prefill
  and decode workers, in the pool's raw block-storage format (native
  and int8/fp8 quantized alike), with retry-then-re-prefill semantics
  at the `mesh.kv_handoff` fault site. `hand_off_async` returns a
  HandoffFuture so the transport copy overlaps the decode pump.
- `router.MeshRouter` — the front door: DRR/priority admission over a
  mesh-wide view, headroom-ranked replica choice behind the
  `mesh.route` fault site and per-replica CircuitBreakers, at-most-once
  stream commit, and replica-failover re-prefill that keeps greedy
  streams byte-identical to a single-replica run.
- `transport` — the versioned length-prefixed frame protocol
  (`mesh.transport_send` fault site), EngineProxy mirroring the engine
  duck-type over it, and ProcessReplicaPool running each replica as a
  child process (`worker.py`) holding its own mesh lease.
- `controller.MeshController` — consumes AutoscaleAdvisor verdicts and
  ACTS: spawn + lease-register on scale_up, drain-before-tombstone on
  scale_down; any failure latches it back to advisory-only
  (`mesh.controller_act` fault site).
- `health` (round 21) — gray-failure immunity: every transport op
  carries a deadline budget (typed `TransportTimeout` past it, the
  replica stays gray, never latched lost), a `HealthDetector` scores
  per-replica progress into healthy / slow / dead verdicts (SLOW is
  demoted from routing, only DEAD walks the replica_down path), and
  the router hedges placements that outlive a quantile latency budget
  — first finish wins through the at-most-once commit map
  (`mesh.net_delay` / `mesh.net_stall` fault sites).

Operational story: RESILIENCE.md "Mesh runbook" + "Process mesh
runbook"; metrics: OBSERVABILITY.md "serving mesh" rows.
"""

from .controller import MeshController
from .handoff import (HandoffFuture, KVHandoffError, hand_off,
                      hand_off_async, pack_record, unpack_record,
                      wire_size)
from .health import HealthDetector, LatencyBudget, VERDICTS
from .replica import Replica, ReplicaPool, ROLES
from .router import MeshRequest, MeshRouter
from .transport import (EngineProxy, LoopbackClient, ProcessReplica,
                        ProcessReplicaPool, SocketClient, TransportError,
                        TransportTimeout, pack_frame, serve_request,
                        unpack_frame)

__all__ = ["KVHandoffError", "hand_off", "hand_off_async",
           "HandoffFuture", "pack_record", "unpack_record", "wire_size",
           "Replica", "ReplicaPool", "ROLES", "MeshRequest",
           "MeshRouter", "TransportError", "TransportTimeout",
           "pack_frame", "unpack_frame",
           "serve_request", "LoopbackClient", "SocketClient",
           "EngineProxy", "ProcessReplica", "ProcessReplicaPool",
           "MeshController", "HealthDetector", "LatencyBudget",
           "VERDICTS"]
