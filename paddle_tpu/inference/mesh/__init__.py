"""Disaggregated serving mesh (round 16).

Turns the single-process ContinuousBatchingEngine into a cluster of
in-process worker replicas:

- `replica.ReplicaPool` — N engine replicas (optionally TP-sharded via
  the PR-12 auto-parallel passes) with lease-based membership over
  TCPStore + ElasticManager; killing one tombstones its lease.
- `handoff` — byte-exact serialized paged-KV transfer between prefill
  and decode workers, in the pool's raw block-storage format (native
  and int8/fp8 quantized alike), with retry-then-re-prefill semantics
  at the `mesh.kv_handoff` fault site.
- `router.MeshRouter` — the front door: DRR/priority admission over a
  mesh-wide view, headroom-ranked replica choice behind the
  `mesh.route` fault site and per-replica CircuitBreakers, at-most-once
  stream commit, and replica-failover re-prefill that keeps greedy
  streams byte-identical to a single-replica run.

Operational story: RESILIENCE.md "Mesh runbook"; metrics:
OBSERVABILITY.md "serving mesh" rows.
"""

from .handoff import (KVHandoffError, hand_off, pack_record,
                      unpack_record, wire_size)
from .replica import Replica, ReplicaPool, ROLES
from .router import MeshRequest, MeshRouter

__all__ = ["KVHandoffError", "hand_off", "pack_record", "unpack_record",
           "wire_size", "Replica", "ReplicaPool", "ROLES",
           "MeshRequest", "MeshRouter"]
