"""Progress-scored replica health for the serving mesh (round 21).

A crash-only failure model misses the failures a real fleet hits most:
workers that are alive-but-wrong — wedged in a step, paused by the OS,
behind a saturated NIC. The transport's typed timeouts make those waits
BOUNDED; this module decides what they MEAN, from the one signal that
cannot lie: progress.

`HealthDetector` keeps a phi-accrual-style suspicion score per replica
(Hayashibara et al.'s accrual detector, the Cassandra/Akka lineage):
every pump the router reports whether the replica is busy and a tuple of
its progress counters (steps, harvested streams, tokens). While a BUSY
replica's counters move, inter-progress intervals feed a per-replica
window and suspicion stays 0. When the counters stop moving, suspicion
phi = elapsed / (mean_interval * ln 10) grows continuously — phi = 3
means "this silence is ~10^3 times past plausible". Two thresholds
yield three verdicts:

  healthy  -> normal ranking
  slow     -> demoted out of `_ranked` (no NEW placements; existing
              streams keep running and the hedger covers them) —
              counted mesh_slow_demotions_total, reversible the moment
              progress resumes
  dead     -> the existing replica_down path (tombstone + breaker slam
              + re-prefill on survivors)

Elapsed-time floors (slow_elapsed_s / dead_elapsed_s) gate both
verdicts so a fast replica with microsecond intervals cannot be killed
by one scheduling hiccup: a verdict needs the score AND real wall
silence. An idle replica is never suspect — no work owed, no expected
progress.

`LatencyBudget` is the hedging trigger: observed placed->commit service
times on fixed geometric buckets, read back through THE shared
estimator (`observability/quantiles.quantile_from_cumulative` — the
same code SLO verdicts use, so "p95 service" can never mean two
things). budget() returns quantile * multiplier, or None until enough
samples landed to trust it.
"""

from __future__ import annotations

import math
from collections import deque

from ...observability.quantiles import quantile_from_cumulative

__all__ = ["VERDICTS", "HealthDetector", "LatencyBudget"]

# the closed verdict registry (static_check closes mesh code and the
# RESILIENCE.md runbook over these keys, both directions)
VERDICTS = {
    "healthy": "progressing (or idle): full member of the routing rank",
    "slow": "busy without progress past the slow thresholds: demoted "
            "from new placements, hedged around, NOT killed — recovers "
            "the moment a progress counter moves",
    "dead": "busy without progress past the dead thresholds: handed to "
            "the replica_down path (tombstone, breaker slam, "
            "re-prefill on survivors)",
}

_LN10 = math.log(10.0)


class _Track:
    __slots__ = ("progress", "last_t", "busy", "intervals")

    def __init__(self, window):
        self.progress = None     # last progress tuple seen
        self.last_t = None       # when it last moved (or went idle)
        self.busy = False
        self.intervals = deque(maxlen=window)


class HealthDetector:
    """Per-replica suspicion scoring. observe() is called once per
    router pump per replica; it returns (verdict, phi) and keeps all
    state internally. forget() drops a replica (killed/retired) so a
    respawn under the same name starts clean."""

    def __init__(self, slow_phi=1.0, dead_phi=8.0, slow_elapsed_s=0.25,
                 dead_elapsed_s=2.0, window=32, floor_s=0.005,
                 prior_interval_s=0.25):
        self.slow_phi = float(slow_phi)
        self.dead_phi = float(dead_phi)
        self.slow_elapsed_s = float(slow_elapsed_s)
        self.dead_elapsed_s = float(dead_elapsed_s)
        self.window = int(window)
        self.floor_s = float(floor_s)
        # mean interval assumed before a replica's first observed
        # progress (a fresh replica that stalls immediately must still
        # accrue suspicion from SOMETHING)
        self.prior_interval_s = float(prior_interval_s)
        self._tracks = {}

    def forget(self, name):
        self._tracks.pop(name, None)

    def _mean_interval(self, st):
        if not st.intervals:
            return self.prior_interval_s
        return max(self.floor_s,
                   sum(st.intervals) / len(st.intervals))

    def suspicion(self, name, now):
        """Current phi for one replica (0.0 = no basis for suspicion)."""
        st = self._tracks.get(name)
        if st is None or st.last_t is None or not st.busy:
            return 0.0
        elapsed = max(0.0, now - st.last_t)
        return elapsed / (self._mean_interval(st) * _LN10)

    def observe(self, name, now, busy, progress):
        """One pump's report -> (verdict, phi). `progress` is any
        comparable tuple of monotone counters; ANY movement resets
        suspicion and (if the replica was busy) feeds the interval
        window."""
        st = self._tracks.get(name)
        if st is None:
            st = self._tracks[name] = _Track(self.window)
        if st.progress != progress:
            if st.last_t is not None and st.busy:
                st.intervals.append(max(self.floor_s, now - st.last_t))
            st.progress = progress
            st.last_t = now
        elif not busy:
            # idle: no work owed, no expected progress — the clock
            # only starts once work shows up again
            st.last_t = now
        elif st.last_t is None or not st.busy:
            # first work ever, or work arriving after an idle stretch:
            # the silence clock starts NOW — the idle gap itself is not
            # suspicion (without this, idle->busy scores the whole gap
            # and one pump can kill a freshly-loaded replica)
            st.last_t = now
        st.busy = bool(busy)
        phi = self.suspicion(name, now)
        verdict = "healthy"
        if st.busy:
            elapsed = now - st.last_t
            if elapsed >= self.slow_elapsed_s and phi >= self.slow_phi:
                verdict = "slow"
                if (elapsed >= self.dead_elapsed_s
                        and phi >= self.dead_phi):
                    verdict = "dead"
        return verdict, phi


# geometric bounds ~1ms .. 64s — wide enough for a tiny test engine and
# a real prefill; +Inf overflow clamps at 64s via the shared estimator
_BUDGET_BOUNDS = tuple(0.001 * (2.0 ** i) for i in range(17)) + (
    float("inf"),)


class LatencyBudget:
    """Quantile-of-observed-service hedging budget on cumulative
    histogram buckets (read through quantile_from_cumulative — THE
    estimator)."""

    def __init__(self, q=0.95, multiplier=2.0, floor_s=0.05,
                 min_samples=4):
        self.q = float(q)
        self.multiplier = float(multiplier)
        self.floor_s = float(floor_s)
        self.min_samples = int(min_samples)
        self._counts = [0] * len(_BUDGET_BOUNDS)
        self.n = 0

    def observe(self, seconds):
        s = float(seconds)
        for i, le in enumerate(_BUDGET_BOUNDS):
            if s <= le:
                self._counts[i] += 1
                break
        self.n += 1

    def budget(self):
        """Seconds a placement may run before it is hedge-worthy, or
        None while uncalibrated (too few samples = no hedging, never a
        guessed budget)."""
        if self.n < self.min_samples:
            return None
        cum, c = [], 0
        for le, cnt in zip(_BUDGET_BOUNDS, self._counts):
            c += cnt
            cum.append((le, c))
        est = quantile_from_cumulative(cum, self.q)
        if est is None:
            return None
        return max(self.floor_s, est * self.multiplier)
