"""Replica pool for the disaggregated serving mesh.

A `Replica` wraps one ContinuousBatchingEngine with a role (prefill /
decode / both), a per-replica CircuitBreaker (the router's failover
signal), and cumulative step-time accounting. A `ReplicaPool` builds N
of them as in-process workers — the CPU-proxy shape of N separate
serving processes — and runs their membership through the real
distributed substrate: every replica registers a lease with an
ElasticManager over a shared TCPStore, the pool beats the leases
synchronously each pump (deterministic: no heartbeat threads in tests),
and killing a replica tombstones its lease so `alive()` drops it the
same way a lost process drops out of an etcd registry.

Replicas may be TP-sharded: with `tp=True` each engine is built under
the PR-12 auto-parallel `mesh_scope`, so its compiled prefill/decode
programs go through the sharding-propagation + overlap passes against a
1-D model-parallel mesh (silently skipped when fewer than 2 devices are
visible — the passes degrade to unsharded jit anyway).
"""

from __future__ import annotations

import time

from ...distributed.store import TCPStore
from ...distributed.fleet.elastic import ElasticManager
from ...resilience.retry import CircuitBreaker, RetryPolicy

__all__ = ["Replica", "ReplicaPool", "ROLES"]

ROLES = ("both", "prefill", "decode")


class Replica:
    """One engine worker in the mesh: engine + role + breaker + the
    accounting the router balances and reports on."""

    __slots__ = ("name", "engine", "role", "breaker", "alive", "draining",
                 "routed", "step_seconds", "steps", "manager",
                 "finished_count", "tokens_out", "sampler")

    def __init__(self, name, engine, role="both",
                 failure_threshold=3, reset_timeout=30.0):
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}; "
                             f"one of {ROLES}")
        self.name = name
        self.engine = engine
        self.role = role
        self.breaker = CircuitBreaker(failure_threshold=failure_threshold,
                                      reset_timeout=reset_timeout,
                                      op=f"mesh.replica.{name}")
        self.alive = True
        self.draining = False    # controller scale-down victim: the
                                 # router stops placing new work here
        self.routed = 0          # requests the router committed here
        self.step_seconds = 0.0  # cumulative engine.step wall on this worker
        self.steps = 0
        self.finished_count = 0  # streams harvested off this worker
        self.tokens_out = 0      # tokens those streams committed
        self.manager = None      # bound by ReplicaPool
        # per-replica observability sampler: attached by the router's
        # MeshCollector (federation.py) and ticked from its pump; a dead
        # replica keeps the sampler so its series freeze, not vanish
        self.sampler = None

    def can_prefill(self):
        return self.role in ("both", "prefill")

    def can_decode(self):
        return self.role in ("both", "decode")

    def load(self):
        """Queued + occupied + parked work — the router's tiebreaker
        when the cost model has not calibrated yet."""
        eng = self.engine
        return (len(eng.queue)
                + sum(r is not None for r in eng.lanes)
                + len(eng._preempted))

    def step(self):
        """One engine step, walled. Returns the step's wall seconds (0.0
        when the engine was idle) — the router folds these into the
        simulated-parallel mesh clock."""
        if not self.engine.has_work():
            return 0.0
        t0 = time.perf_counter()
        self.engine.step()
        dt = time.perf_counter() - t0
        self.step_seconds += dt
        self.steps += 1
        return dt

    def brownout_level(self):
        """The worker's current brownout rung (0 = normal): read off
        its scheduler in-process, mirrored from the last step reply for
        process-backed workers. The router's ranking DEMOTES browned-out
        replicas — a hint, never a correctness input."""
        sch = getattr(self.engine, "scheduler", None)
        if sch is not None:
            return int(getattr(sch, "level", 0))
        return int(getattr(self.engine, "brownout_level", 0))

    def snapshot(self):
        """Per-replica slice of the mesh report: liveness, routing and
        SLO-capacity state."""
        eng = self.engine
        svc = eng.predicted_service_seconds()
        # harvested streams plus whatever finished since the last pump
        tokens = self.tokens_out + sum(len(r.generated)
                                       for r in eng.finished.values())
        return {
            "role": self.role,
            "alive": self.alive,
            "draining": self.draining,
            "serving_brownout_level": self.brownout_level(),
            "breaker": self.breaker.state,
            "routed": self.routed,
            "finished": self.finished_count + len(eng.finished),
            "tokens": tokens,
            "steps": self.steps,
            "step_seconds": round(self.step_seconds, 4),
            "tok_per_s": (round(tokens / self.step_seconds, 1)
                          if self.step_seconds > 0 else None),
            "predicted_service_s": svc,
            "load": self.load(),
        }


def _build_sharded(build_engine, tp):
    """Build one engine, optionally under the PR-12 auto-parallel mesh
    scope so its PIR-compiled programs are sharding-propagated."""
    if not tp:
        return build_engine()
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 2:
        return build_engine()   # passes would degrade to unsharded anyway
    mesh = Mesh(np.array(devs[:2]).reshape(2), ("mp",))
    from ...pir.shard_prop import mesh_scope
    with mesh_scope(mesh):
        return build_engine()


class ReplicaPool:
    """N in-process engine replicas with lease-based membership.

    build_engine: zero-arg engine factory (called N times; seed inside
    the factory for identical replicas — disaggregation requires every
    worker to hold the same weights).
    roles: per-replica role list, or None for the default split:
    n == 1 -> ("both",); disaggregate -> first half prefill, second
    half decode (at least one of each); else all "both".
    """

    def __init__(self, build_engine, n=2, roles=None, disaggregate=False,
                 tp=False, store=None, store_port=46101,
                 heartbeat_interval=5.0, failure_threshold=3,
                 reset_timeout=30.0):
        n = int(n)
        if n < 1:
            raise ValueError("a mesh needs at least one replica")
        if roles is None:
            if disaggregate and n >= 2:
                n_prefill = max(1, n // 2)
                roles = (["prefill"] * n_prefill
                         + ["decode"] * (n - n_prefill))
            else:
                roles = ["both"] * n
        if len(roles) != n:
            raise ValueError(f"{len(roles)} roles for {n} replicas")
        if disaggregate and n >= 2:
            if not any(r in ("both", "prefill") for r in roles):
                raise ValueError("disaggregated mesh has no prefill worker")
            if not any(r in ("both", "decode") for r in roles):
                raise ValueError("disaggregated mesh has no decode worker")
        self.disaggregate = bool(disaggregate) and n >= 2
        self._build_engine = build_engine
        self._tp = bool(tp)
        self._hb_interval = float(heartbeat_interval)
        self._failure_threshold = failure_threshold
        self._reset_timeout = reset_timeout
        self._next_idx = n      # spawn() names stay unique after retires
        # membership substrate: one shared in-process store, one elastic
        # lease per replica. Heartbeats are synchronous (beat()) so the
        # pool is deterministic under test; production workers would
        # call manager.start() for the threaded loop instead.
        self.store = store if store is not None else TCPStore(
            is_master=True, port=store_port, timeout=2)
        self._retry = RetryPolicy(max_attempts=2, base_delay=0.01,
                                  seed=0, sleep=lambda _s: None)
        self.replicas = []
        for i in range(n):
            rep = self._make_replica(i, roles[i], failure_threshold,
                                     reset_timeout)
            self._bind_membership(rep, n)
            self.replicas.append(rep)

    def _build_one_engine(self):
        return _build_sharded(self._build_engine, self._tp)

    def _make_replica(self, i, role, failure_threshold, reset_timeout):
        """Build one worker (subclass hook: ProcessReplicaPool builds
        transport-backed proxies here instead of in-process engines)."""
        return Replica(f"replica{i}", self._build_one_engine(),
                       role=role, failure_threshold=failure_threshold,
                       reset_timeout=reset_timeout)

    def _bind_membership(self, rep, n):
        """Register the replica's lease (subclass hook: socket workers
        register their OWN lease from the child process)."""
        rep.manager = ElasticManager(
            self.store, node_id=rep.name, np_range=(1, n),
            heartbeat_interval=self._hb_interval,
            retry_policy=self._retry)
        rep.manager.register()

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def __getitem__(self, i):
        return self.replicas[i]

    def by_name(self, name):
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(name)

    def alive(self):
        return [rep for rep in self.replicas if rep.alive]

    def beat(self):
        """Refresh every live replica's lease (synchronous heartbeat —
        one store write per replica)."""
        for rep in self.replicas:
            if rep.alive:
                rep.manager._beat()

    def alive_nodes(self):
        """Membership as the store sees it (lease-fresh, tombstones
        dropped) — the cross-check that the elastic registry agrees
        with the pool's own liveness flags."""
        if not self.replicas:
            return []
        return self.replicas[0].manager.alive_nodes()

    def kill(self, name):
        """Simulate losing a replica process: tombstone its lease, mark
        it dead, and force its breaker open so the router fails fast
        instead of probing a corpse. The engine object is NOT drained —
        exactly like a killed process, whatever it was doing is gone;
        the router re-prefills its uncommitted streams elsewhere."""
        rep = self.by_name(name)
        if not rep.alive:
            return rep
        rep.alive = False
        rep.manager.deregister()
        for _ in range(rep.breaker.failure_threshold):
            rep.breaker.record_failure()
        return rep

    def spawn(self, role="both"):
        """Grow the pool live: build one new worker, register its
        lease, and add it to the rotation (the MeshController's
        scale-up action). The new replica draws traffic as soon as the
        router's next ranking sees it."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}; "
                             f"one of {ROLES}")
        i = self._next_idx
        self._next_idx += 1
        rep = self._make_replica(i, role, self._failure_threshold,
                                 self._reset_timeout)
        self._bind_membership(rep, len(self.replicas) + 1)
        self.replicas.append(rep)
        return rep

    def retire(self, name):
        """Clean scale-down exit for a DRAINED worker: tombstone its
        lease and drop it from the rotation. Unlike kill(), the engine
        was idle — nothing is lost, no breaker slam, no failover."""
        rep = self.by_name(name)
        if not rep.alive:
            return rep
        rep.alive = False
        rep.draining = False
        rep.manager.deregister()
        return rep

    def prefill_targets(self):
        return [r for r in self.alive() if r.can_prefill()]

    def decode_targets(self):
        return [r for r in self.alive() if r.can_decode()]
