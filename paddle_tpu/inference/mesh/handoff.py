"""Serialized paged-KV handoff between disaggregated serving workers.

A prefill worker's `engine.export_kv` record — the prompt's KV blocks in
the pool's RAW storage representation (payload + scales when quantized)
plus the stream's identity (trace id, PRNG seed, arrival anchors) — is
packed into one self-describing wire buffer, transferred, and installed
on a decode worker through `engine.import_kv`. Because the payload is
the stored bytes (never dequantized values), the round trip is
byte-exact for every KVBlockFormat: native/bf16 passthrough and
int8/fp8 quantized alike, so the decode side continues the stream
byte-identically to a single-process engine.

Failure contract (`mesh.kv_handoff` fault site): transient transfer
failures retry under the caller's RetryPolicy; exhaustion raises
KVHandoffError and the router falls back to RE-PREFILLING the request
on the decode side — slower, never wrong (greedy decode is
deterministic and sampled lanes key the device PRNG on (seed, absolute
position), so the re-prefilled stream is the same stream).

Wire format (version 1): little-endian u32 header length, a sorted-key
JSON header (scalar metadata + per-array dtype/shape manifest), then the
arrays' raw bytes concatenated in sorted key order. Deterministic — the
same record packs to the same bytes.
"""

from __future__ import annotations

import io
import json
import struct

import numpy as np

from ...resilience.faults import FaultInjected, fault_point

__all__ = ["KVHandoffError", "pack_record", "unpack_record", "wire_size",
           "hand_off", "hand_off_async", "HandoffFuture"]

_TRANSIENT = (TimeoutError, ConnectionError, OSError, FaultInjected)

WIRE_VERSION = 1


class KVHandoffError(RuntimeError):
    """A paged-KV handoff that could not be delivered (transient
    failures past the retry budget, or the receiving engine rejected
    the record). The router's recovery is re-prefill, not a crash."""


def _py(v):
    """JSON-safe scalar: numpy ints/floats/bools -> Python builtins."""
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v


def _resolve_dtype(name):
    """np.dtype by name, falling back to ml_dtypes for the extended
    float formats (bfloat16 / float8_*) jax stores KV payloads in."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_record(record):
    """Serialize an export_kv record to one wire buffer (bytes)."""
    meta, arrays = {}, {}
    for key, val in record.items():
        if isinstance(val, np.ndarray):
            arrays[key] = np.ascontiguousarray(val)
        else:
            meta[key] = _py(val)
    meta["wire_version"] = WIRE_VERSION
    manifest = {k: [str(a.dtype), list(a.shape)]
                for k, a in arrays.items()}
    head = json.dumps({"meta": meta, "arrays": manifest},
                      sort_keys=True).encode()
    out = io.BytesIO()
    out.write(struct.pack("<I", len(head)))
    out.write(head)
    for key in sorted(arrays):
        out.write(arrays[key].tobytes())
    return out.getvalue()


def unpack_record(buf):
    """Inverse of pack_record; array bytes round-trip exactly."""
    (hlen,) = struct.unpack_from("<I", buf, 0)
    head = json.loads(buf[4:4 + hlen].decode())
    meta = head["meta"]
    if meta.get("wire_version") != WIRE_VERSION:
        raise KVHandoffError(
            f"unknown handoff wire version {meta.get('wire_version')!r}")
    record = {k: v for k, v in meta.items() if k != "wire_version"}
    off = 4 + hlen
    for key in sorted(head["arrays"]):
        dtype_name, shape = head["arrays"][key]
        dt = _resolve_dtype(dtype_name)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        end = off + n * dt.itemsize
        record[key] = np.frombuffer(
            buf[off:end], dtype=dt).reshape(shape).copy()
        off = end
    if "prompt" in record:
        record["prompt"] = np.asarray(record["prompt"], np.int32)
    return record


def wire_size(record):
    """Wire bytes this record serializes to (the handoff-bytes
    histogram's unit) without keeping the buffer."""
    return len(pack_record(record))


def hand_off(record, engine, retry=None):
    """Deliver one prefill->decode handoff: pass the `mesh.kv_handoff`
    fault site, pack + unpack the record over the wire, and install it
    on `engine` via import_kv. Returns (local_rid, wire_bytes,
    retries). Raises KVHandoffError when the transfer cannot be
    delivered (caller re-prefills); engine-side rejections
    (format mismatch, pool exhausted) propagate as KVHandoffError too.

    The transfer itself is host-side bytes — on the in-process CPU
    proxy it runs between engine steps while the decode engine's
    double-buffered tiles are still in flight, i.e. overlapped with
    decode exactly as a NIC transfer would be; import_kv only parks the
    request, so no device work serializes behind the copy."""
    def _xfer():
        fault_point("mesh.kv_handoff", trace=record.get("trace_id"))
        return pack_record(record)

    try:
        if retry is not None:
            wire = retry.call(_xfer, op="mesh.kv_handoff")
            retries = retry.last_retries
        else:
            wire = _xfer()
            retries = 0
    except _TRANSIENT as e:
        raise KVHandoffError(f"handoff transfer failed: {e!r}") from e
    try:
        rid = engine.import_kv(unpack_record(wire))
    except (ValueError, MemoryError) as e:
        raise KVHandoffError(f"receiving engine rejected handoff: "
                             f"{e!r}") from e
    except _TRANSIENT as e:
        # a process-backed engine's import crosses the transport — its
        # death mid-import is a failed transfer, not a rejection
        raise KVHandoffError(f"handoff transfer failed: {e!r}") from e
    return rid, len(wire), retries


class HandoffFuture:
    """Delivery-complete handle for one asynchronous handoff. done() is
    a non-blocking poll; result() forces completion and returns
    (local_rid, wire_bytes, retries) or raises KVHandoffError with the
    same cause classification as hand_off (rejection cause ValueError/
    MemoryError -> caller tries the next target; transient cause ->
    caller re-prefills)."""

    __slots__ = ("_inner", "_nbytes", "_retries", "_resolved", "_value",
                 "_exc")

    def __init__(self, inner=None, nbytes=0, retries=0):
        self._inner = inner     # the transport future, when remote
        self._nbytes = int(nbytes)
        self._retries = int(retries)
        self._resolved = False
        self._value = None
        self._exc = None

    def _complete(self, value):
        self._resolved = True
        self._value = value

    def _fail(self, exc):
        self._resolved = True
        self._exc = exc

    def _translate(self, force):
        if self._resolved or self._inner is None:
            return
        if not force and not self._inner.done():
            return
        try:
            out = self._inner.result()
            if isinstance(out, tuple):      # transport (meta, payload)
                out = out[0]["rid"]
            self._complete((int(out), self._nbytes, self._retries))
        except (ValueError, MemoryError) as e:
            err = KVHandoffError(
                f"receiving engine rejected handoff: {e!r}")
            err.__cause__ = e
            self._fail(err)
        except _TRANSIENT as e:
            err = KVHandoffError(f"handoff transfer failed: {e!r}")
            err.__cause__ = e
            self._fail(err)

    def done(self):
        if not self._resolved and self._inner is not None \
                and self._inner.done():
            self._translate(force=True)
        return self._resolved

    def result(self):
        self._translate(force=True)
        if not self._resolved:
            raise KVHandoffError("handoff future never resolved")
        if self._exc is not None:
            raise self._exc
        return self._value


def hand_off_async(record, engine, retry=None):
    """hand_off, asynchronously: the `mesh.kv_handoff` fault/retry
    contract runs NOW (the site arms before bytes move, so a retried
    pack never double-imports), the transport copy overlaps with the
    caller's pump, and the returned HandoffFuture completes on
    delivery. Engines without `import_kv_async` (in-process pools)
    resolve synchronously through hand_off — behavior byte-identical to
    every earlier round."""
    importer = getattr(engine, "import_kv_async", None)
    if importer is None:
        fut = HandoffFuture()
        try:
            fut._complete(hand_off(record, engine, retry=retry))
        except KVHandoffError as e:
            fut._fail(e)
        return fut

    def _xfer():
        fault_point("mesh.kv_handoff", trace=record.get("trace_id"))
        return pack_record(record)

    try:
        if retry is not None:
            wire = retry.call(_xfer, op="mesh.kv_handoff")
            retries = retry.last_retries
        else:
            wire = _xfer()
            retries = 0
    except _TRANSIENT as e:
        fut = HandoffFuture()
        err = KVHandoffError(f"handoff transfer failed: {e!r}")
        err.__cause__ = e
        fut._fail(err)
        return fut
    return HandoffFuture(inner=importer(unpack_record(wire)),
                         nbytes=len(wire), retries=retries)
