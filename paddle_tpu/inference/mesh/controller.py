"""MeshController: the autoscale loop closed — verdicts become actions.

PR 14's AutoscaleAdvisor is advisory by design: it emits
hysteresis-gated ±1 scale verdicts and drain-time predictions, and the
mesh ignores them. The MeshController consumes those verdicts and ACTS
on the live ReplicaPool:

  scale_up     pool.spawn(): build + lease-register a new worker; it
               draws traffic on the router's next ranking pass.
  scale_down   pick a victim (role invariants preserved: a
               disaggregated mesh always keeps >=1 prefill and >=1
               decode worker), mark it DRAINING — the router stops
               placing new work there, in-flight streams finish through
               the existing preemption/handoff machinery — then retire
               it: tombstone the lease only when the worker is idle.
               A drain that exceeds `drain_rounds` pumps is FORCED
               through router.kill_replica, i.e. the drilled
               re-prefill-on-survivors path — slower, never wrong.

Every action is flight-recorded (kind "controller") and counted
(`mesh_controller_actions_total{action}`). Failure contract
(`mesh.controller_act` fault site): ANY controller exception latches it
back to advisory-only (enabled=False, counted latch_off +
serving_runtime_degradations_total{what=controller_advisory}) while
serving continues byte-identically — the controller can only ever make
the pool bigger/smaller, never touch a stream.
"""

from __future__ import annotations

from ...observability.catalog import metric as _metric
from ...observability.recorder import get_recorder as _get_recorder
from ...resilience.faults import fault_point

__all__ = ["MeshController"]


class MeshController:
    """controller = MeshController(router, max_replicas=4)
    router.controller = controller     # acted on every pump
    """

    def __init__(self, router, min_replicas=1, max_replicas=4,
                 drain_rounds=50, spawn_role="auto"):
        self.router = router
        self.pool = router.pool
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.drain_rounds = max(1, int(drain_rounds))
        self.spawn_role = spawn_role
        self.enabled = True
        self.actions = {"scale_up": 0, "drain_begin": 0, "scale_down": 0,
                        "drain_forced": 0, "latch_off": 0}
        self._drain_waits: dict[str, int] = {}
        self._rec = _get_recorder()

    # --- accounting -------------------------------------------------------
    def _action(self, action, **detail):
        self.actions[action] += 1
        _metric("mesh_controller_actions_total", action=action).inc()
        if self._rec.enabled:
            self._rec.record("controller", action=action, **detail)

    # --- the acting loop --------------------------------------------------
    def act(self, verdict=None):
        """One controller tick from the router pump: progress any
        in-flight drain, then act on the verdict (None / hold = drains
        only). Latches to advisory-only on ANY failure."""
        if not self.enabled:
            return
        try:
            fault_point("mesh.controller_act",
                        action=None if verdict is None
                        else verdict.get("action"))
            self._pump_drains()
            if verdict is not None:
                self._act(verdict)
        except Exception as e:  # noqa: BLE001 — latch, never break serving
            self.enabled = False
            self.actions["latch_off"] += 1
            _metric("mesh_controller_actions_total",
                    action="latch_off").inc()
            _metric("serving_runtime_degradations_total",
                    what="controller_advisory").inc()
            if self._rec.enabled:
                self._rec.record("controller", action="latch_off",
                                 error=repr(e))

    def _act(self, verdict):
        action = verdict.get("action")
        alive = self.pool.alive()
        if action == "scale_up":
            if len(alive) >= self.max_replicas or self._drain_waits:
                return      # at ceiling, or mid-drain: do not flap
            role = self.spawn_role
            if role == "auto":
                role = "decode" if self.pool.disaggregate else "both"
            rep = self.pool.spawn(role=role)
            self._action("scale_up", replica=rep.name, role=rep.role)
        elif action == "scale_down":
            if len(alive) <= self.min_replicas or self._drain_waits:
                return      # at floor, or one drain at a time
            victim = self._pick_victim(alive)
            if victim is None:
                return      # no candidate keeps the role invariants
            victim.draining = True
            self._drain_waits[victim.name] = 0
            self._action("drain_begin", replica=victim.name,
                         load=victim.load())

    def _pick_victim(self, alive):
        """Least-loaded worker whose removal keeps the pool routable:
        in a disaggregated mesh at least one prefill-capable and one
        decode-capable worker must survive."""
        def survives(rep):
            rest = [r for r in alive if r is not rep]
            if not rest:
                return False
            if self.pool.disaggregate:
                return (any(r.can_prefill() for r in rest)
                        and any(r.can_decode() for r in rest))
            return True
        cands = [r for r in alive if survives(r)]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load(), r.name))

    def _drained(self, rep):
        """Idle = nothing queued/occupied/parked on the worker, nothing
        finished-but-unharvested, and no mesh-side stream still assigned
        to it (harvest runs before the controller in the pump, so this
        is a stable read)."""
        if rep.load() > 0 or rep.engine.finished:
            return False
        return not any(not m.done and m.replica == rep.name
                       for m in self.router._open.values())

    def _pump_drains(self):
        for name in list(self._drain_waits):
            rep = self.pool.by_name(name)
            if not rep.alive:       # died mid-drain: failover handled it
                del self._drain_waits[name]
                continue
            if self._drained(rep):
                del self._drain_waits[name]
                self.pool.retire(name)
                self._action("scale_down", replica=name)
                continue
            self._drain_waits[name] += 1
            if self._drain_waits[name] > self.drain_rounds:
                # the victim would not drain (stuck stream, slow decode
                # budget): force it through the drilled kill path — its
                # uncommitted streams re-prefill on survivors,
                # byte-identical
                del self._drain_waits[name]
                self._action("drain_forced", replica=name)
                self.router.kill_replica(name, why="drain_forced")
